"""Client-side robustness primitives shared by all four front-ends
(HTTP/gRPC x sync/asyncio): retry with exponential backoff + full
jitter, a circuit breaker, and the retry executors that wire both into
a client call.

Design notes
------------

* :class:`RetryPolicy` is immutable configuration — one instance can be
  shared across every client and worker thread in a process. Mutable
  retry state (attempt counters, backoff draws) lives in the executor's
  stack frame, never on the policy.
* Backoff uses **full jitter** (``uniform(0, min(cap, base * mult^n))``)
  rather than equal jitter: under a thundering herd the uniform spread
  over the whole interval decorrelates clients fastest.
* The per-call deadline is a **shrinking budget**: every attempt is
  handed the wall-clock remaining out of the caller's ``client_timeout``
  so the total time (attempts + backoffs) never exceeds what the caller
  asked for. A retry whose backoff would not leave room for another
  attempt re-raises immediately instead of sleeping into a guaranteed
  deadline miss.
* :class:`CircuitBreaker` is per-client (per connection target), not
  global: closed -> open after ``failure_threshold`` consecutive
  failures, open -> half-open after ``reset_timeout_s``, half-open
  admits exactly one probe whose outcome decides closed vs open again.
  While open, calls fail fast with ``UNAVAILABLE`` — no network I/O —
  which is what sheds load from a struggling server.
* :class:`EndpointPool` lifts all of the above from one connection to a
  replica fleet: one breaker + EWMA latency per endpoint (passive
  health), least-outstanding routing with a latency tiebreak, sticky
  routing by ``sequence_id``, an optional background prober that
  readmits ejected endpoints (active health), and budgeted request
  hedging per "The Tail at Scale" (Dean & Barroso, 2013).
  :func:`call_with_retry_pool` / :func:`call_with_retry_pool_async` are
  the pool-aware twins of the single-endpoint executors: a retryable
  failure fails over to the next healthy endpoint inside the same
  shrinking ``client_timeout`` budget.
"""

from __future__ import annotations

import queue as _queue
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from client_tpu import status_map as _status_map
from client_tpu.utils import InferenceServerException

# Statuses worth retrying by default: server-side admission rejections
# and transport failures surface as UNAVAILABLE (gRPC) / 503 (HTTP);
# per-tenant quota rejects surface as RESOURCE_EXHAUSTED (gRPC) / 429
# (HTTP) and carry a Retry-After derived from the token-bucket refill
# time, which retry_after_of turns into the minimum backoff — the
# retry is paced to when the server SAID capacity returns.
# Deadline expiries are NOT default-retryable — a request that timed
# out once will usually time out again and retrying it doubles load at
# exactly the moment the server is slowest.
# (The string<->code vocabulary itself lives in client_tpu/status_map —
# one canonical table for servers and clients alike.)
DEFAULT_RETRYABLE_STATUSES = _status_map.DEFAULT_RETRYABLE_WIRE

# Statuses that justify FAILOVER to a different endpoint even though
# they are not retryable against the same one: a server cancelling
# in-flight work (shutdown grace expiring) says this replica is going
# away, not that the request was bad. Caller-side cancellation never
# takes this shape — it surfaces as CancelledError/FutureCancelledError
# (BaseExceptions), not a status-CANCELLED server exception.
POOL_FAILOVER_STATUSES = frozenset({"CANCELLED"})

# Definitive client errors: the server answered, decisively — proof
# the endpoint is healthy. These feed the circuit breaker as
# successes; everything else (availability errors, timeouts, server
# errors, status-less transport failures) counts toward opening it.
CLIENT_ERROR_STATUSES = _status_map.CLIENT_ERROR_WIRE

# Per-tenant quota rejects: retryable (paced by Retry-After) but
# POLICY signals, not availability evidence — the server answered
# decisively and is healthy, it just chose not to admit THIS tenant
# yet. Counting them as breaker failures would let one over-quota
# tenant open the circuit / eject a healthy endpoint for all traffic
# sharing the client.
QUOTA_REJECT_STATUSES = _status_map.QUOTA_REJECT_WIRE


def _breaker_resolve(breaker: "CircuitBreaker", error: BaseException) -> None:
    """Settle the breaker after a failed attempt. A definitive client
    error (bad shape, unknown model) proves the server is up and must
    not open the circuit against a healthy endpoint; caller-side
    aborts (cancellation, interrupts — BaseExceptions that are not
    Exceptions) say nothing about the server, so they only free the
    probe slot; anything else is availability evidence. Every path
    resolves a half-open probe — a probe left unresolved would lock
    the client out forever."""
    if isinstance(error, InferenceServerException) \
            and ((error.status() or "") in CLIENT_ERROR_STATUSES
                 or (error.status() or "") in QUOTA_REJECT_STATUSES):
        breaker.record_success()
    elif not isinstance(error, Exception):
        # asyncio.CancelledError / KeyboardInterrupt / SystemExit: the
        # CALLER gave up, the server never answered either way.
        breaker.abort_probe()
    else:
        breaker.record_failure()


class RetryPolicy:
    """Immutable retry configuration (share one instance freely).

    ``max_attempts`` counts the first try: ``max_attempts=4`` means one
    call plus up to three retries.
    """

    def __init__(
        self,
        max_attempts: int = 4,
        initial_backoff_s: float = 0.025,
        backoff_multiplier: float = 2.0,
        max_backoff_s: float = 1.0,
        retryable_statuses=DEFAULT_RETRYABLE_STATUSES,
        jitter: bool = True,
        rng: Optional[random.Random] = None,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.initial_backoff_s = float(initial_backoff_s)
        self.backoff_multiplier = float(backoff_multiplier)
        self.max_backoff_s = float(max_backoff_s)
        self.retryable_statuses = frozenset(
            str(s) for s in retryable_statuses)
        self.jitter = bool(jitter)
        self._rng = rng if rng is not None else random.Random()

    def is_retryable(self, error: Exception) -> bool:
        if not isinstance(error, InferenceServerException):
            return False
        return (error.status() or "") in self.retryable_statuses

    def backoff_cap_s(self, attempt: int) -> float:
        """Deterministic upper bound of the attempt's backoff draw."""
        cap = self.initial_backoff_s * (self.backoff_multiplier ** attempt)
        return min(cap, self.max_backoff_s)

    def backoff_s(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (0-based: the wait
        after the first failure is ``backoff_s(0)``)."""
        cap = self.backoff_cap_s(attempt)
        if not self.jitter:
            return cap
        return self._rng.uniform(0.0, cap)


class CircuitBreaker:
    """Consecutive-failure circuit breaker with a half-open probe.

    Thread-safe; intended to be owned by one client talking to one
    endpoint. ``before_call`` raises ``UNAVAILABLE`` while the circuit
    is open (fail fast, zero network I/O), admits a single probe once
    ``reset_timeout_s`` has elapsed, and the executor reports the
    outcome through ``record_success`` / ``record_failure``.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def before_call(self) -> None:
        with self._lock:
            if self._state == self.OPEN:
                waited = self._clock() - self._opened_at
                if waited < self.reset_timeout_s:
                    raise InferenceServerException(
                        "circuit breaker open after %d consecutive "
                        "failures; next probe in %.2fs"
                        % (self._consecutive_failures,
                           self.reset_timeout_s - waited),
                        status="UNAVAILABLE",
                    )
                self._state = self.HALF_OPEN
                self._probe_in_flight = True
                return
            if self._state == self.HALF_OPEN:
                if self._probe_in_flight:
                    raise InferenceServerException(
                        "circuit breaker half-open: probe already in "
                        "flight", status="UNAVAILABLE")
                self._probe_in_flight = True

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._consecutive_failures = 0
            self._probe_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if (self._state == self.HALF_OPEN
                    or self._consecutive_failures >= self.failure_threshold):
                self._state = self.OPEN
                self._opened_at = self._clock()
            self._probe_in_flight = False

    def admits(self) -> bool:
        """Non-mutating preview of :meth:`before_call`: would a call
        be allowed right now? Used by the retry executors to skip the
        backoff sleep when the circuit has just opened — sleeping
        toward an attempt the breaker will refuse only delays the
        caller's failure."""
        with self._lock:
            if self._state == self.OPEN:
                return self._clock() - self._opened_at \
                    >= self.reset_timeout_s
            if self._state == self.HALF_OPEN:
                return not self._probe_in_flight
            return True

    def abort_probe(self) -> None:
        """Settle an aborted call with NO availability evidence: the
        failure counter is untouched and a half-open probe slot is
        freed (back to open with the original timer, so the next call
        may probe immediately)."""
        with self._lock:
            self._probe_in_flight = False
            if self._state == self.HALF_OPEN:
                self._state = self.OPEN


# -- process-wide retry accounting (the perf harness's chaos report
# sums retries across every per-worker client). `exhausted` counts
# retryable failures that escaped to the caller anyway (attempts or
# deadline budget spent) — the honest "not recovered" number: it spans
# the whole process lifetime exactly like the chaos injection
# counters, so the recovery rate compares like with like (per-window
# error counts would miss warm-up-window failures). ------------------

_retry_lock = threading.Lock()
_retry_total = 0
_exhausted_total = 0
# Fleet accounting (EndpointPool): summed across every pool in the
# process so the perf harness's failover report spans all workers,
# exactly like the retry counters above.
_failover_total = 0
_hedge_fired_total = 0
_hedge_won_total = 0
_ejection_total = 0
_readmission_total = 0


def note_retries(count: int = 1) -> None:
    global _retry_total
    with _retry_lock:
        _retry_total += count


def note_exhausted() -> None:
    global _exhausted_total
    with _retry_lock:
        _exhausted_total += 1


def retry_total() -> int:
    with _retry_lock:
        return _retry_total


def exhausted_total() -> int:
    with _retry_lock:
        return _exhausted_total


def _note_fleet(counter: str) -> None:
    global _failover_total, _hedge_fired_total, _hedge_won_total, \
        _ejection_total, _readmission_total
    with _retry_lock:
        if counter == "failover":
            _failover_total += 1
        elif counter == "hedge_fired":
            _hedge_fired_total += 1
        elif counter == "hedge_won":
            _hedge_won_total += 1
        elif counter == "ejection":
            _ejection_total += 1
        elif counter == "readmission":
            _readmission_total += 1


def fleet_totals() -> dict:
    """Process-lifetime EndpointPool counters (all pools summed)."""
    with _retry_lock:
        return {
            "failovers": _failover_total,
            "hedges_fired": _hedge_fired_total,
            "hedges_won": _hedge_won_total,
            "ejections": _ejection_total,
            "readmissions": _readmission_total,
        }


def reset_retry_total() -> None:
    global _retry_total, _exhausted_total, _failover_total, \
        _hedge_fired_total, _hedge_won_total, _ejection_total, \
        _readmission_total
    with _retry_lock:
        _retry_total = 0
        _exhausted_total = 0
        _failover_total = 0
        _hedge_fired_total = 0
        _hedge_won_total = 0
        _ejection_total = 0
        _readmission_total = 0


def _note_if_exhausted(policy: Optional[RetryPolicy],
                       error: InferenceServerException) -> None:
    """A retryable-class error is escaping to the caller: count it as
    unrecovered (attempts/budget spent, or no policy to retry with)."""
    statuses = (policy.retryable_statuses if policy is not None
                else frozenset(DEFAULT_RETRYABLE_STATUSES))
    if (error.status() or "") in statuses:
        note_exhausted()


def retry_after_of(error: BaseException) -> Optional[float]:
    """Server-advised retry delay riding on the error (the HTTP
    ``Retry-After`` header / the gRPC ``retry-after`` trailing-metadata
    hint), seconds; None when the server sent none."""
    value = getattr(error, "retry_after_s", None)
    if value is None:
        return None
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    return value if value > 0 else None


def _next_delay(policy: RetryPolicy, error: InferenceServerException,
                attempt: int, deadline_s: Optional[float],
                elapsed_s: float) -> Optional[float]:
    """Backoff before the next attempt, or None when the call must
    re-raise (non-retryable, attempts exhausted, or no budget left to
    retry inside the deadline)."""
    if not policy.is_retryable(error):
        return None
    if attempt >= policy.max_attempts - 1:
        return None
    delay = policy.backoff_s(attempt)
    retry_after = retry_after_of(error)
    if retry_after is not None:
        # The server knows its queue better than our jitter does:
        # sleep at least as long as it asked, still capped by the
        # policy ceiling so a hostile header can't park the client.
        delay = min(max(delay, retry_after), policy.max_backoff_s)
    if deadline_s is not None and elapsed_s + delay >= deadline_s:
        return None
    return delay


def call_with_retry(
    fn: Callable[[Optional[float]], object],
    policy: Optional[RetryPolicy] = None,
    breaker: Optional[CircuitBreaker] = None,
    deadline_s: Optional[float] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    cancel_fn: Optional[Callable[[], None]] = None,
):
    """Run ``fn(remaining_timeout_s)`` under the retry policy.

    ``fn`` receives the wall-clock budget remaining out of
    ``deadline_s`` (None when no deadline) and should pass it through
    as its transport timeout, so later attempts get strictly less time.
    Only :class:`InferenceServerException` is ever retried; breaker
    open-state failures raise without consuming retry attempts.
    ``cancel_fn`` (best-effort, e.g. ``POST /v2/cancel/<id>``) fires
    before a retry that follows a client-side DEADLINE_EXCEEDED: the
    timed-out attempt was *abandoned*, not answered — without the
    cancel the server keeps computing a response nobody will read
    while the retry doubles the load.
    """
    start = clock()
    attempt = 0
    while True:
        if breaker is not None:
            try:
                # Outside the retry net: open circuits fail fast
                # instead of burning attempts — but the shed call IS a
                # client-visible unrecovered failure, so count it.
                breaker.before_call()
            except InferenceServerException as e:
                _note_if_exhausted(policy, e)
                raise
        remaining = None
        if deadline_s is not None:
            remaining = deadline_s - (clock() - start)
            if remaining <= 0:
                raise InferenceServerException(
                    "deadline of %.3fs exhausted after %d attempt(s)"
                    % (deadline_s, attempt), status="DEADLINE_EXCEEDED")
        try:
            result = fn(remaining)
        except InferenceServerException as e:
            if breaker is not None:
                _breaker_resolve(breaker, e)
            delay = None if policy is None else _next_delay(
                policy, e, attempt, deadline_s, clock() - start)
            if delay is None or (breaker is not None
                                 and not breaker.admits()):
                # No retry coming (attempts/budget spent, or the
                # breaker just opened): raise the REAL error now —
                # sleeping first and counting a phantom retry would
                # only delay the failure and skew the chaos report.
                _note_if_exhausted(policy, e)
                raise
            if cancel_fn is not None \
                    and (e.status() or "") == "DEADLINE_EXCEEDED":
                # Client-timeout failover: the abandoned attempt may
                # still be computing server-side.
                try:
                    cancel_fn()
                except Exception:  # noqa: BLE001 — best-effort signal
                    pass
            note_retries()
            sleep(delay)
            attempt += 1
            continue
        except BaseException as e:
            # Unexpected failures (decode bugs, KeyboardInterrupt,
            # cancellation) are never retried, but they MUST still
            # settle the breaker — an unresolved half-open probe locks
            # the client out.
            if breaker is not None:
                _breaker_resolve(breaker, e)
            raise
        if breaker is not None:
            breaker.record_success()
        return result


async def call_with_retry_async(
    fn,
    policy: Optional[RetryPolicy] = None,
    breaker: Optional[CircuitBreaker] = None,
    deadline_s: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
):
    """asyncio mirror of :func:`call_with_retry`; ``fn`` is an async
    callable taking the remaining-timeout budget."""
    import asyncio

    start = clock()
    attempt = 0
    while True:
        if breaker is not None:
            try:
                breaker.before_call()
            except InferenceServerException as e:
                # A shed call is a client-visible unrecovered failure.
                _note_if_exhausted(policy, e)
                raise
        remaining = None
        if deadline_s is not None:
            remaining = deadline_s - (clock() - start)
            if remaining <= 0:
                raise InferenceServerException(
                    "deadline of %.3fs exhausted after %d attempt(s)"
                    % (deadline_s, attempt), status="DEADLINE_EXCEEDED")
        try:
            result = await fn(remaining)
        except InferenceServerException as e:
            if breaker is not None:
                _breaker_resolve(breaker, e)
            delay = None if policy is None else _next_delay(
                policy, e, attempt, deadline_s, clock() - start)
            if delay is None or (breaker is not None
                                 and not breaker.admits()):
                # See the sync executor: never sleep toward an attempt
                # the breaker will refuse.
                _note_if_exhausted(policy, e)
                raise
            note_retries()
            await asyncio.sleep(delay)
            attempt += 1
            continue
        except BaseException as e:
            # See the sync executor: every failure (incl. task
            # cancellation) settles the breaker.
            if breaker is not None:
                _breaker_resolve(breaker, e)
            raise
        if breaker is not None:
            breaker.record_success()
        return result


# -- endpoint pool: health-aware multi-endpoint routing + hedging ----------


class EndpointState:
    """Per-endpoint health + load record owned by an EndpointPool.

    Mutable fields are guarded by the POOL's lock (routing reads the
    whole fleet atomically); the breaker has its own lock and is safe
    to touch directly.
    """

    def __init__(self, url: str, breaker: CircuitBreaker):
        self.url = url
        self.breaker = breaker
        self.outstanding = 0       # requests currently in flight
        self.ewma_latency_s = 0.0  # 0 until the first sample
        self.requests = 0
        self.failures = 0
        # Last breaker state the pool observed — the edge detector for
        # the ejection/readmission counters (the breaker itself has no
        # transition hooks).
        self.last_state = CircuitBreaker.CLOSED


class EndpointPool:
    """A fleet of interchangeable server endpoints with passive and
    active health tracking, least-outstanding routing, sticky sequence
    routing, and budgeted request hedging.

    * **Passive health**: every call settles the endpoint's
      :class:`CircuitBreaker` (ejection = breaker open) and, on
      success, its EWMA latency. Definitive client errors count as
      health, exactly like the single-endpoint executors.
    * **Active health**: :meth:`ensure_prober` runs a background
      thread that half-open-probes ejected endpoints with a bounded
      health check and readmits them on recovery — so a replica that
      comes back is found by the prober, not by sacrificial traffic.
    * **Routing**: least expected completion time —
      ``(outstanding + 1) * EWMA latency`` — so a latency-degraded
      replica sheds traffic long before it fails anything, with a
      small uniform exploration ratio (``explore_ratio``) so a
      recovered replica's latency estimate refreshes instead of
      freezing at its worst. ``sequence_id`` pins correlated streams
      to one endpoint until it is ejected (implicit server-side state
      is endpoint-local).
    * **Hedging**: after ``hedge_delay_s()`` (the pool's observed
      latency quantile, floored at ``hedge_delay_min_ms``) the
      executors may fire the same idempotent request at a second
      endpoint; first success wins. ``hedge_max_ratio`` budgets hedges
      against total requests so a brown-out cannot double fleet load.

    One pool may be shared by many clients (the perf harness shares a
    pool across worker clients so the fleet-health view and the
    counters span the whole run); transports stay per-client.
    """

    def __init__(self, urls, breaker_factory: Optional[Callable[[], CircuitBreaker]] = None,
                 hedge_delay_min_ms: float = 1.0,
                 hedge_quantile: float = 0.95,
                 hedge_max_ratio: float = 0.05,
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 1.0,
                 latency_window: int = 512,
                 explore_ratio: float = 0.02,
                 hedge_workers: int = 32,
                 rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic):
        urls = self.split_url(urls)
        if not urls:
            raise ValueError("EndpointPool needs at least one url")
        if len(set(urls)) != len(urls):
            raise ValueError("EndpointPool urls must be distinct: %r" % urls)
        factory = breaker_factory or CircuitBreaker
        self._clock = clock
        self._lock = threading.Lock()
        self.endpoints: Dict[str, EndpointState] = {
            url: EndpointState(url, factory()) for url in urls
        }
        self.hedge_delay_min_ms = float(hedge_delay_min_ms)
        self.hedge_quantile = min(max(float(hedge_quantile), 0.0), 1.0)
        self.hedge_max_ratio = max(float(hedge_max_ratio), 0.0)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.explore_ratio = min(max(float(explore_ratio), 0.0), 1.0)
        self._rng = rng if rng is not None else random.Random()
        self._latencies: List[float] = []  # ring buffer of success samples
        self._latency_window = max(int(latency_window), 16)
        self._latency_idx = 0
        self._sticky: Dict[int, str] = {}
        # counters (also mirrored into the process-wide fleet totals)
        self.requests_total = 0
        self.hedges_fired = 0
        self.hedges_won = 0
        self.hedges_discarded = 0
        self.failovers = 0
        self.ejections = 0
        self.readmissions = 0
        self.probes = 0
        self._prober_thread: Optional[threading.Thread] = None
        self._prober_stop = threading.Event()
        # Worker pool for the SYNC hedged path: reused threads (no
        # per-call thread churn), bounded by a semaphore so saturation
        # degrades to inline unhedged attempts instead of queueing
        # primaries behind each other.
        self._worker_count = max(int(hedge_workers), 2)
        self._worker_slots = threading.BoundedSemaphore(self._worker_count)
        self._workers = None

    def _acquire_worker(self):
        """Non-blocking worker-slot acquire; returns the executor or
        None when every slot is busy (caller degrades to inline)."""
        if not self._worker_slots.acquire(blocking=False):
            return None
        with self._lock:
            if self._workers is None:
                from concurrent.futures import ThreadPoolExecutor

                self._workers = ThreadPoolExecutor(
                    max_workers=self._worker_count,
                    thread_name_prefix="endpoint-pool-hedge")
            return self._workers

    def _release_worker(self) -> None:
        self._worker_slots.release()

    # -- construction helpers -------------------------------------------

    @staticmethod
    def split_url(url) -> List[str]:
        """Accepts ``"a:1,b:1"``, ``["a:1", "b:1"]``, or a single url;
        returns the cleaned endpoint list."""
        if isinstance(url, str):
            parts = [u.strip() for u in url.split(",")]
        elif isinstance(url, Sequence):
            parts = [str(u).strip() for u in url]
        else:
            parts = [str(url).strip()]
        return [u for u in parts if u]

    def __len__(self) -> int:
        return len(self.endpoints)

    @property
    def urls(self) -> List[str]:
        return list(self.endpoints)

    # -- routing ---------------------------------------------------------

    def _admitting(self, exclude) -> List[EndpointState]:
        return [s for s in self.endpoints.values()
                if s.url not in exclude and s.breaker.admits()]

    @staticmethod
    def _score(state: EndpointState) -> float:
        """Expected completion time: queue depth x per-request latency.
        A replica 30x slower sheds traffic even while idle, instead of
        looking attractive every time it drains its one request."""
        return (state.outstanding + 1) * max(state.ewma_latency_s, 1e-6)

    def pick(self, exclude=(), sequence_id: int = 0) -> EndpointState:
        """Choose the endpoint for one attempt: sticky by sequence_id
        while the pinned endpoint stays healthy, else minimum expected
        completion time (with a small uniform exploration draw that
        keeps every endpoint's latency estimate fresh). Raises
        UNAVAILABLE when no endpoint admits a call (every breaker
        open)."""
        exclude = set(exclude)
        with self._lock:
            if sequence_id:
                pinned = self._sticky.get(sequence_id)
                if pinned is not None and pinned not in exclude:
                    state = self.endpoints.get(pinned)
                    if state is not None and state.breaker.admits():
                        return state
            candidates = self._admitting(exclude)
            if not candidates:
                raise InferenceServerException(
                    "no healthy endpoint in pool (%d of %d ejected%s)"
                    % (sum(1 for s in self.endpoints.values()
                           if s.breaker.state != CircuitBreaker.CLOSED),
                       len(self.endpoints),
                       ", %d excluded" % len(exclude) if exclude else ""),
                    status="UNAVAILABLE")
            if len(candidates) > 1 and not sequence_id \
                    and self._rng.random() < self.explore_ratio:
                state = self._rng.choice(candidates)
            else:
                state = min(candidates, key=self._score)
            if sequence_id:
                previous = self._sticky.get(sequence_id)
                self._sticky[sequence_id] = state.url
                if previous is not None and previous != state.url \
                        and previous not in exclude:
                    # the pinned endpoint was ejected mid-sequence: the
                    # re-pin IS a failover even before any attempt
                    # runs. (When the caller EXCLUDED the pin — the
                    # retry loop failing over after an attempt — that
                    # loop already counted it; counting here too would
                    # double-book one event.)
                    self.failovers += 1
                    _note_fleet("failover")
            return state

    def has_alternative(self, exclude=()) -> bool:
        with self._lock:
            return bool(self._admitting(set(exclude)))

    def release_sequence(self, sequence_id: int) -> None:
        with self._lock:
            self._sticky.pop(sequence_id, None)

    # -- passive health bookkeeping -------------------------------------

    def _check_transition(self, state: EndpointState) -> None:
        """Edge-detect breaker transitions (caller holds the lock)."""
        now = state.breaker.state
        if now == state.last_state:
            return
        if now == CircuitBreaker.OPEN \
                and state.last_state != CircuitBreaker.OPEN:
            self.ejections += 1
            _note_fleet("ejection")
        elif now == CircuitBreaker.CLOSED \
                and state.last_state == CircuitBreaker.OPEN:
            self.readmissions += 1
            _note_fleet("readmission")
        elif now == CircuitBreaker.CLOSED \
                and state.last_state == CircuitBreaker.HALF_OPEN:
            self.readmissions += 1
            _note_fleet("readmission")
        state.last_state = now

    def note_start(self, state: EndpointState) -> None:
        with self._lock:
            state.outstanding += 1
            state.requests += 1

    def note_end(self, state: EndpointState, latency_s: float,
                 error: Optional[BaseException] = None,
                 sample: bool = True) -> None:
        """``sample=False`` keeps the latency out of the hedge-delay
        quantile window while still updating the endpoint's EWMA: a
        hedge LOSER's latency is real evidence about its endpoint, but
        the caller never waited for it — letting losers into the window
        would drag the hedge delay toward exactly the slow latencies
        hedging is meant to cut."""
        if error is None:
            state.breaker.record_success()
        else:
            _breaker_resolve(state.breaker, error)
        with self._lock:
            state.outstanding = max(state.outstanding - 1, 0)
            if error is None:
                state.ewma_latency_s = (
                    latency_s if state.ewma_latency_s == 0.0
                    else 0.2 * latency_s + 0.8 * state.ewma_latency_s)
                if sample:
                    if len(self._latencies) < self._latency_window:
                        self._latencies.append(latency_s)
                    else:
                        self._latencies[self._latency_idx] = latency_s
                        self._latency_idx = \
                            (self._latency_idx + 1) % self._latency_window
            else:
                state.failures += 1
            self._check_transition(state)

    def note_request(self) -> None:
        with self._lock:
            self.requests_total += 1

    def note_failover(self) -> None:
        with self._lock:
            self.failovers += 1
        _note_fleet("failover")

    def note_hedge_won(self) -> None:
        with self._lock:
            self.hedges_won += 1
        _note_fleet("hedge_won")

    def note_hedge_discarded(self) -> None:
        with self._lock:
            self.hedges_discarded += 1

    # -- hedging ---------------------------------------------------------

    def hedge_delay_s(self) -> float:
        """Delay before firing a hedge: the configured quantile of
        observed latencies, floored at ``hedge_delay_min_ms`` (and a
        10ms default while the sample window is still cold)."""
        floor = self.hedge_delay_min_ms / 1000.0
        with self._lock:
            samples = sorted(self._latencies)
        if len(samples) < 8:
            return max(floor, 0.01)
        idx = min(int(self.hedge_quantile * len(samples)),
                  len(samples) - 1)
        return max(floor, samples[idx])

    def try_acquire_hedge(self, exclude=()) -> Optional[EndpointState]:
        """Budget gate + routing for one hedge: returns the endpoint to
        hedge on (debiting the budget), or None when the budget is
        spent or no distinct healthy endpoint exists."""
        exclude = set(exclude)
        with self._lock:
            if self.hedge_max_ratio <= 0:
                return None
            if (self.hedges_fired + 1) > \
                    self.hedge_max_ratio * max(self.requests_total, 1):
                return None
            candidates = self._admitting(exclude)
            if not candidates:
                return None
            # No exploration for hedges: the hedge exists to BEAT the
            # slow attempt, so it always takes the best endpoint.
            state = min(candidates, key=self._score)
            self.hedges_fired += 1
        _note_fleet("hedge_fired")
        return state

    # -- active probing ---------------------------------------------------

    def ensure_prober(self, probe_fn: Callable[[str], bool]) -> None:
        """Start the background prober (idempotent). ``probe_fn(url)``
        must be a BOUNDED health check returning truthy on a live+ready
        endpoint; exceptions count as failure. The prober only touches
        endpoints whose breaker is not closed, using the breaker's own
        half-open probe slot, so it never races traffic into a double
        probe and never adds load to healthy replicas."""
        with self._lock:
            if self._prober_thread is not None \
                    and self._prober_thread.is_alive():
                return
            self._prober_stop.clear()
            self._prober_thread = threading.Thread(
                target=self._probe_loop, args=(probe_fn,), daemon=True,
                name="endpoint-pool-prober")
            self._prober_thread.start()

    def _probe_loop(self, probe_fn: Callable[[str], bool]) -> None:
        while not self._prober_stop.wait(self.probe_interval_s):
            for state in list(self.endpoints.values()):
                if self._prober_stop.is_set():
                    return
                breaker = state.breaker
                if breaker.state == CircuitBreaker.CLOSED \
                        or not breaker.admits():
                    continue
                try:
                    breaker.before_call()
                except InferenceServerException:
                    continue  # raced a traffic probe into the slot
                with self._lock:
                    self.probes += 1
                try:
                    ok = bool(probe_fn(state.url))
                except Exception:
                    ok = False
                if ok:
                    breaker.record_success()
                else:
                    breaker.record_failure()
                with self._lock:
                    self._check_transition(state)

    def stop_prober(self) -> None:
        with self._lock:
            thread, self._prober_thread = self._prober_thread, None
        self._prober_stop.set()
        if thread is not None:
            thread.join(timeout=5)

    def close(self) -> None:
        self.stop_prober()
        with self._lock:
            workers, self._workers = self._workers, None
        if workers is not None:
            workers.shutdown(wait=False)

    # -- observability ----------------------------------------------------

    def stats(self) -> dict:
        """Snapshot of fleet health + the hedging/failover counters."""
        hedge_delay_ms = round(self.hedge_delay_s() * 1000.0, 3)
        with self._lock:
            endpoints = [
                {
                    "url": s.url,
                    "state": s.breaker.state,
                    "outstanding": s.outstanding,
                    "ewma_latency_ms": round(s.ewma_latency_s * 1000.0, 3),
                    "requests": s.requests,
                    "failures": s.failures,
                }
                for s in self.endpoints.values()
            ]
            return {
                "endpoints": endpoints,
                "requests": self.requests_total,
                "hedges_fired": self.hedges_fired,
                "hedges_won": self.hedges_won,
                "hedges_discarded": self.hedges_discarded,
                "failovers": self.failovers,
                "ejections": self.ejections,
                "readmissions": self.readmissions,
                "probes": self.probes,
                "hedge_delay_ms": hedge_delay_ms,
            }


# -- pool-aware executors --------------------------------------------------


def _pool_attempt(pool: EndpointPool, state: EndpointState, fn,
                  remaining: Optional[float], clock, sample_fn=None):
    """One attempt against one endpoint with full breaker + latency
    bookkeeping. ``fn(endpoint_state, remaining_timeout_s)``;
    ``sample_fn`` decides at completion time whether the latency enters
    the hedge-delay window (hedge losers don't)."""
    state.breaker.before_call()
    pool.note_start(state)
    t0 = clock()
    try:
        result = fn(state, remaining)
    except BaseException as e:
        pool.note_end(state, clock() - t0, error=e)
        raise
    pool.note_end(state, clock() - t0,
                  sample=sample_fn() if sample_fn is not None else True)
    return result


def _remaining_of(deadline_s, start, clock):
    if deadline_s is None:
        return None
    remaining = deadline_s - (clock() - start)
    if remaining <= 0:
        raise InferenceServerException(
            "deadline of %.3fs exhausted" % deadline_s,
            status="DEADLINE_EXCEEDED")
    return remaining


def _hedged_call(pool: EndpointPool, fn, primary: EndpointState,
                 deadline_s: Optional[float], start: float, clock,
                 hedge: bool, cancel_fn=None):
    """Run one logical attempt, optionally hedged: the primary runs on
    a worker thread; if it hasn't answered within the pool's hedge
    delay and the budget admits, the same request fires at a second
    endpoint and the first SUCCESS wins. The loser is not silently
    discarded: ``cancel_fn(endpoint_state)`` (when provided) sends a
    real wire cancel for the still-pending attempt, so budgeted
    hedging stops double-charging the fleet — Dean & Barroso's
    tied-request rule. Falls back to a plain inline attempt when
    hedging can't apply."""
    workers = None
    if hedge and pool.hedge_max_ratio > 0 and len(pool) >= 2:
        # Reused worker threads, bounded: when every slot is busy the
        # call degrades to a plain inline attempt (hedging is
        # opportunistic — queueing primaries behind each other to
        # preserve it would invert the latency win).
        workers = pool._acquire_worker()
    if workers is None:
        return _pool_attempt(pool, primary, fn,
                             _remaining_of(deadline_s, start, clock), clock)

    outcomes: "_queue.Queue" = _queue.Queue()
    settled = threading.Event()  # a winner already returned

    def run(state: EndpointState) -> None:
        try:
            try:
                remaining = _remaining_of(deadline_s, start, clock)
                result = _pool_attempt(
                    pool, state, fn, remaining, clock,
                    sample_fn=lambda: not settled.is_set())
            except BaseException as e:  # noqa: BLE001 — via the queue
                outcomes.put((state, None, e))
                return
            if settled.is_set():
                pool.note_hedge_discarded()
            outcomes.put((state, result, None))
        finally:
            pool._release_worker()

    workers.submit(run, primary)
    launched = [primary]
    first = None
    try:
        first = outcomes.get(timeout=pool.hedge_delay_s())
    except _queue.Empty:
        hedge_state = None
        hedge_workers = pool._acquire_worker()
        if hedge_workers is not None:
            hedge_state = pool.try_acquire_hedge(exclude={primary.url})
            if hedge_state is None:
                pool._release_worker()
        if hedge_state is not None:
            hedge_workers.submit(run, hedge_state)
            launched.append(hedge_state)

    errors = []
    pending = len(launched) - (1 if first is not None else 0)
    item = first
    while True:
        if item is None:
            # Bounded wait: each attempt already carries the shrinking
            # transport budget, the slack only covers scheduling.
            timeout = None
            if deadline_s is not None:
                timeout = max(deadline_s - (clock() - start), 0.0) + 0.25
            try:
                item = outcomes.get(timeout=timeout)
            except _queue.Empty:
                raise InferenceServerException(
                    "deadline of %.3fs exhausted waiting for hedged "
                    "attempts" % deadline_s, status="DEADLINE_EXCEEDED")
            pending -= 1
        state, result, error = item
        item = None
        if error is None:
            settled.set()
            if len(launched) > 1 and state is launched[1]:
                pool.note_hedge_won()
            if cancel_fn is not None and pending > 0:
                # A winner settled while attempts are still in flight:
                # wire-cancel each pending loser instead of letting
                # its server compute a response nobody reads.
                finished = {id(state)}
                finished.update(id(s) for s, _ in errors)
                for loser in launched:
                    if id(loser) not in finished:
                        try:
                            cancel_fn(loser)
                        except Exception:  # noqa: BLE001 — best-effort
                            pass
            return result
        errors.append((state, error))
        if pending <= 0:
            break
    # every launched attempt failed: surface the primary's error (the
    # hedge was opportunistic; its failure is secondary evidence)
    for state, error in errors:
        if state is primary:
            raise error
    raise errors[0][1]


def call_with_retry_pool(
    fn,
    pool: EndpointPool,
    policy: Optional[RetryPolicy] = None,
    deadline_s: Optional[float] = None,
    sequence_id: int = 0,
    sequence_end: bool = False,
    hedge: bool = True,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    cancel_fn=None,
):
    """Pool-aware twin of :func:`call_with_retry`.

    ``fn(endpoint_state, remaining_timeout_s)`` runs one attempt
    against one endpoint. A retryable failure fails over to the next
    healthy endpoint immediately (no backoff — a different replica is
    not the one that just failed); when every endpoint has been tried
    the backoff applies before the fleet is retried from scratch.
    Without a policy the budget is one attempt per endpoint (pure
    failover). Sequence-correlated requests (``sequence_id``) are
    sticky-routed and never hedged; ``sequence_end`` releases the pin.
    ``cancel_fn(endpoint_state)`` wire-cancels a hedge loser's
    still-pending attempt at that endpoint (best-effort).
    """
    start = clock()
    attempt = 0
    tried: set = set()
    pool.note_request()
    max_attempts = policy.max_attempts if policy is not None \
        else max(len(pool), 1)
    retryable_statuses = (policy.retryable_statuses if policy is not None
                          else frozenset(DEFAULT_RETRYABLE_STATUSES))
    while True:
        remaining = deadline_s
        if deadline_s is not None:
            remaining = deadline_s - (clock() - start)
            if remaining <= 0:
                raise InferenceServerException(
                    "deadline of %.3fs exhausted after %d attempt(s)"
                    % (deadline_s, attempt), status="DEADLINE_EXCEEDED")
        try:
            state = pool.pick(exclude=tried, sequence_id=sequence_id)
        except InferenceServerException as e:
            if tried:
                tried = set()  # whole fleet tried: widen back out
                try:
                    state = pool.pick(sequence_id=sequence_id)
                except InferenceServerException as e2:
                    _note_if_exhausted(policy, e2)
                    raise
            else:
                _note_if_exhausted(policy, e)
                raise
        try:
            result = _hedged_call(pool, fn, state, deadline_s, start,
                                  clock, hedge and not sequence_id,
                                  cancel_fn=cancel_fn)
        except InferenceServerException as e:
            status = e.status() or ""
            retryable = (policy.is_retryable(e) if policy is not None
                         else status in retryable_statuses)
            # Endpoint-level failures (see POOL_FAILOVER_STATUSES) are
            # failover-eligible even when not same-endpoint-retryable.
            retryable = retryable or status in POOL_FAILOVER_STATUSES
            if not retryable or attempt >= max_attempts - 1:
                if sequence_id and sequence_end:
                    # the sequence is over even on failure: a leaked
                    # pin would grow _sticky forever and stale-route a
                    # reused sequence_id
                    pool.release_sequence(sequence_id)
                _note_if_exhausted(policy, e)
                raise
            tried.add(state.url)
            # Quota rejects never fail over: quotas are enforced on
            # every replica, so "try the next endpoint now" turns one
            # throttled tenant's request into fleet-size physical hits
            # and skips the Retry-After pacing the server asked for.
            # They take the backoff path (floored at Retry-After).
            if status not in QUOTA_REJECT_STATUSES \
                    and pool.has_alternative(exclude=tried):
                # Immediate failover: a healthy replica exists, so
                # sleeping first would only stretch the tail.
                pool.note_failover()
                note_retries()
                attempt += 1
                continue
            delay = None if policy is None else _next_delay(
                policy, e, attempt, deadline_s, clock() - start)
            if delay is None:
                _note_if_exhausted(policy, e)
                raise
            note_retries()
            sleep(delay)
            tried = set()
            attempt += 1
            continue
        if sequence_id and sequence_end:
            pool.release_sequence(sequence_id)
        return result


async def _pool_attempt_async(pool: EndpointPool, state: EndpointState,
                              fn, remaining: Optional[float], clock):
    state.breaker.before_call()
    pool.note_start(state)
    t0 = clock()
    try:
        result = await fn(state, remaining)
    except BaseException as e:
        pool.note_end(state, clock() - t0, error=e)
        raise
    pool.note_end(state, clock() - t0)
    return result


async def _hedged_call_async(pool: EndpointPool, fn,
                             primary: EndpointState,
                             deadline_s: Optional[float], start: float,
                             clock, hedge: bool):
    import asyncio

    if not hedge or pool.hedge_max_ratio <= 0 or len(pool) < 2:
        return await _pool_attempt_async(
            pool, primary, fn, _remaining_of(deadline_s, start, clock),
            clock)

    def spawn(state):
        async def attempt():
            remaining = _remaining_of(deadline_s, start, clock)
            return await _pool_attempt_async(pool, state, fn, remaining,
                                             clock)
        return asyncio.ensure_future(attempt())

    primary_task = spawn(primary)
    done, _ = await asyncio.wait({primary_task},
                                 timeout=pool.hedge_delay_s())
    tasks = {primary_task: primary}
    if not done:
        hedge_state = pool.try_acquire_hedge(exclude={primary.url})
        if hedge_state is not None:
            tasks[spawn(hedge_state)] = hedge_state
    errors = []
    pending = set(tasks)
    while pending:
        done, pending = await asyncio.wait(
            pending, return_when=asyncio.FIRST_COMPLETED)
        for task in done:
            error = task.exception()
            if error is None:
                # winner: cancel the loser (its cancellation settles
                # the breaker neutrally via abort_probe)
                for loser in pending:
                    loser.cancel()
                for loser in pending:
                    try:
                        await loser
                    except BaseException:  # noqa: BLE001 — discarded
                        pass
                if task is not primary_task:
                    pool.note_hedge_won()
                # tpulint: disable=aio-blocking -- task came from
                # asyncio.wait's done set; result() on a settled
                # future returns immediately
                return task.result()
            errors.append((tasks[task], error))
    for state, error in errors:
        if state is primary:
            raise error
    raise errors[0][1]


async def call_with_retry_pool_async(
    fn,
    pool: EndpointPool,
    policy: Optional[RetryPolicy] = None,
    deadline_s: Optional[float] = None,
    sequence_id: int = 0,
    sequence_end: bool = False,
    hedge: bool = True,
    clock: Callable[[], float] = time.monotonic,
):
    """asyncio mirror of :func:`call_with_retry_pool`; ``fn`` is an
    async callable taking (endpoint_state, remaining_timeout_s)."""
    import asyncio

    start = clock()
    attempt = 0
    tried: set = set()
    pool.note_request()
    max_attempts = policy.max_attempts if policy is not None \
        else max(len(pool), 1)
    retryable_statuses = (policy.retryable_statuses if policy is not None
                          else frozenset(DEFAULT_RETRYABLE_STATUSES))
    while True:
        if deadline_s is not None:
            if deadline_s - (clock() - start) <= 0:
                raise InferenceServerException(
                    "deadline of %.3fs exhausted after %d attempt(s)"
                    % (deadline_s, attempt), status="DEADLINE_EXCEEDED")
        try:
            state = pool.pick(exclude=tried, sequence_id=sequence_id)
        except InferenceServerException as e:
            if tried:
                tried = set()
                try:
                    state = pool.pick(sequence_id=sequence_id)
                except InferenceServerException as e2:
                    _note_if_exhausted(policy, e2)
                    raise
            else:
                _note_if_exhausted(policy, e)
                raise
        try:
            result = await _hedged_call_async(
                pool, fn, state, deadline_s, start, clock,
                hedge and not sequence_id)
        except InferenceServerException as e:
            status = e.status() or ""
            retryable = (policy.is_retryable(e) if policy is not None
                         else status in retryable_statuses)
            # Endpoint-level failures (see POOL_FAILOVER_STATUSES) are
            # failover-eligible even when not same-endpoint-retryable.
            retryable = retryable or status in POOL_FAILOVER_STATUSES
            if not retryable or attempt >= max_attempts - 1:
                if sequence_id and sequence_end:
                    # the sequence is over even on failure: a leaked
                    # pin would grow _sticky forever and stale-route a
                    # reused sequence_id
                    pool.release_sequence(sequence_id)
                _note_if_exhausted(policy, e)
                raise
            tried.add(state.url)
            # Same no-failover rule for quota rejects as the sync
            # twin: pace on Retry-After instead of multiplying an
            # over-quota tenant's load by fleet size.
            if status not in QUOTA_REJECT_STATUSES \
                    and pool.has_alternative(exclude=tried):
                pool.note_failover()
                note_retries()
                attempt += 1
                continue
            delay = None if policy is None else _next_delay(
                policy, e, attempt, deadline_s, clock() - start)
            if delay is None:
                _note_if_exhausted(policy, e)
                raise
            note_retries()
            await asyncio.sleep(delay)
            tried = set()
            attempt += 1
            continue
        if sequence_id and sequence_end:
            pool.release_sequence(sequence_id)
        return result
