"""Client-side robustness primitives shared by all four front-ends
(HTTP/gRPC x sync/asyncio): retry with exponential backoff + full
jitter, a circuit breaker, and the retry executors that wire both into
a client call.

Design notes
------------

* :class:`RetryPolicy` is immutable configuration — one instance can be
  shared across every client and worker thread in a process. Mutable
  retry state (attempt counters, backoff draws) lives in the executor's
  stack frame, never on the policy.
* Backoff uses **full jitter** (``uniform(0, min(cap, base * mult^n))``)
  rather than equal jitter: under a thundering herd the uniform spread
  over the whole interval decorrelates clients fastest.
* The per-call deadline is a **shrinking budget**: every attempt is
  handed the wall-clock remaining out of the caller's ``client_timeout``
  so the total time (attempts + backoffs) never exceeds what the caller
  asked for. A retry whose backoff would not leave room for another
  attempt re-raises immediately instead of sleeping into a guaranteed
  deadline miss.
* :class:`CircuitBreaker` is per-client (per connection target), not
  global: closed -> open after ``failure_threshold`` consecutive
  failures, open -> half-open after ``reset_timeout_s``, half-open
  admits exactly one probe whose outcome decides closed vs open again.
  While open, calls fail fast with ``UNAVAILABLE`` — no network I/O —
  which is what sheds load from a struggling server.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

from client_tpu.utils import InferenceServerException

# Statuses worth retrying by default: server-side admission rejections
# and transport failures surface as UNAVAILABLE (gRPC) / 503 (HTTP).
# Deadline expiries are NOT default-retryable — a request that timed
# out once will usually time out again and retrying it doubles load at
# exactly the moment the server is slowest.
DEFAULT_RETRYABLE_STATUSES = ("UNAVAILABLE", "503")

# Definitive client errors: the server answered, decisively — proof
# the endpoint is healthy. These feed the circuit breaker as
# successes; everything else (availability errors, timeouts, server
# errors, status-less transport failures) counts toward opening it.
CLIENT_ERROR_STATUSES = frozenset({
    "INVALID_ARGUMENT", "400", "NOT_FOUND", "404", "ALREADY_EXISTS",
    "409", "UNIMPLEMENTED", "501", "PERMISSION_DENIED", "403",
    "UNAUTHENTICATED", "401",
})


def _breaker_resolve(breaker: "CircuitBreaker", error: BaseException) -> None:
    """Settle the breaker after a failed attempt. A definitive client
    error (bad shape, unknown model) proves the server is up and must
    not open the circuit against a healthy endpoint; caller-side
    aborts (cancellation, interrupts — BaseExceptions that are not
    Exceptions) say nothing about the server, so they only free the
    probe slot; anything else is availability evidence. Every path
    resolves a half-open probe — a probe left unresolved would lock
    the client out forever."""
    if isinstance(error, InferenceServerException) \
            and (error.status() or "") in CLIENT_ERROR_STATUSES:
        breaker.record_success()
    elif not isinstance(error, Exception):
        # asyncio.CancelledError / KeyboardInterrupt / SystemExit: the
        # CALLER gave up, the server never answered either way.
        breaker.abort_probe()
    else:
        breaker.record_failure()


class RetryPolicy:
    """Immutable retry configuration (share one instance freely).

    ``max_attempts`` counts the first try: ``max_attempts=4`` means one
    call plus up to three retries.
    """

    def __init__(
        self,
        max_attempts: int = 4,
        initial_backoff_s: float = 0.025,
        backoff_multiplier: float = 2.0,
        max_backoff_s: float = 1.0,
        retryable_statuses=DEFAULT_RETRYABLE_STATUSES,
        jitter: bool = True,
        rng: Optional[random.Random] = None,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.initial_backoff_s = float(initial_backoff_s)
        self.backoff_multiplier = float(backoff_multiplier)
        self.max_backoff_s = float(max_backoff_s)
        self.retryable_statuses = frozenset(
            str(s) for s in retryable_statuses)
        self.jitter = bool(jitter)
        self._rng = rng if rng is not None else random.Random()

    def is_retryable(self, error: Exception) -> bool:
        if not isinstance(error, InferenceServerException):
            return False
        return (error.status() or "") in self.retryable_statuses

    def backoff_cap_s(self, attempt: int) -> float:
        """Deterministic upper bound of the attempt's backoff draw."""
        cap = self.initial_backoff_s * (self.backoff_multiplier ** attempt)
        return min(cap, self.max_backoff_s)

    def backoff_s(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (0-based: the wait
        after the first failure is ``backoff_s(0)``)."""
        cap = self.backoff_cap_s(attempt)
        if not self.jitter:
            return cap
        return self._rng.uniform(0.0, cap)


class CircuitBreaker:
    """Consecutive-failure circuit breaker with a half-open probe.

    Thread-safe; intended to be owned by one client talking to one
    endpoint. ``before_call`` raises ``UNAVAILABLE`` while the circuit
    is open (fail fast, zero network I/O), admits a single probe once
    ``reset_timeout_s`` has elapsed, and the executor reports the
    outcome through ``record_success`` / ``record_failure``.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def before_call(self) -> None:
        with self._lock:
            if self._state == self.OPEN:
                waited = self._clock() - self._opened_at
                if waited < self.reset_timeout_s:
                    raise InferenceServerException(
                        "circuit breaker open after %d consecutive "
                        "failures; next probe in %.2fs"
                        % (self._consecutive_failures,
                           self.reset_timeout_s - waited),
                        status="UNAVAILABLE",
                    )
                self._state = self.HALF_OPEN
                self._probe_in_flight = True
                return
            if self._state == self.HALF_OPEN:
                if self._probe_in_flight:
                    raise InferenceServerException(
                        "circuit breaker half-open: probe already in "
                        "flight", status="UNAVAILABLE")
                self._probe_in_flight = True

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._consecutive_failures = 0
            self._probe_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if (self._state == self.HALF_OPEN
                    or self._consecutive_failures >= self.failure_threshold):
                self._state = self.OPEN
                self._opened_at = self._clock()
            self._probe_in_flight = False

    def admits(self) -> bool:
        """Non-mutating preview of :meth:`before_call`: would a call
        be allowed right now? Used by the retry executors to skip the
        backoff sleep when the circuit has just opened — sleeping
        toward an attempt the breaker will refuse only delays the
        caller's failure."""
        with self._lock:
            if self._state == self.OPEN:
                return self._clock() - self._opened_at \
                    >= self.reset_timeout_s
            if self._state == self.HALF_OPEN:
                return not self._probe_in_flight
            return True

    def abort_probe(self) -> None:
        """Settle an aborted call with NO availability evidence: the
        failure counter is untouched and a half-open probe slot is
        freed (back to open with the original timer, so the next call
        may probe immediately)."""
        with self._lock:
            self._probe_in_flight = False
            if self._state == self.HALF_OPEN:
                self._state = self.OPEN


# -- process-wide retry accounting (the perf harness's chaos report
# sums retries across every per-worker client). `exhausted` counts
# retryable failures that escaped to the caller anyway (attempts or
# deadline budget spent) — the honest "not recovered" number: it spans
# the whole process lifetime exactly like the chaos injection
# counters, so the recovery rate compares like with like (per-window
# error counts would miss warm-up-window failures). ------------------

_retry_lock = threading.Lock()
_retry_total = 0
_exhausted_total = 0


def note_retries(count: int = 1) -> None:
    global _retry_total
    with _retry_lock:
        _retry_total += count


def note_exhausted() -> None:
    global _exhausted_total
    with _retry_lock:
        _exhausted_total += 1


def retry_total() -> int:
    with _retry_lock:
        return _retry_total


def exhausted_total() -> int:
    with _retry_lock:
        return _exhausted_total


def reset_retry_total() -> None:
    global _retry_total, _exhausted_total
    with _retry_lock:
        _retry_total = 0
        _exhausted_total = 0


def _note_if_exhausted(policy: Optional[RetryPolicy],
                       error: InferenceServerException) -> None:
    """A retryable-class error is escaping to the caller: count it as
    unrecovered (attempts/budget spent, or no policy to retry with)."""
    statuses = (policy.retryable_statuses if policy is not None
                else frozenset(DEFAULT_RETRYABLE_STATUSES))
    if (error.status() or "") in statuses:
        note_exhausted()


def _next_delay(policy: RetryPolicy, error: InferenceServerException,
                attempt: int, deadline_s: Optional[float],
                elapsed_s: float) -> Optional[float]:
    """Backoff before the next attempt, or None when the call must
    re-raise (non-retryable, attempts exhausted, or no budget left to
    retry inside the deadline)."""
    if not policy.is_retryable(error):
        return None
    if attempt >= policy.max_attempts - 1:
        return None
    delay = policy.backoff_s(attempt)
    if deadline_s is not None and elapsed_s + delay >= deadline_s:
        return None
    return delay


def call_with_retry(
    fn: Callable[[Optional[float]], object],
    policy: Optional[RetryPolicy] = None,
    breaker: Optional[CircuitBreaker] = None,
    deadline_s: Optional[float] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
):
    """Run ``fn(remaining_timeout_s)`` under the retry policy.

    ``fn`` receives the wall-clock budget remaining out of
    ``deadline_s`` (None when no deadline) and should pass it through
    as its transport timeout, so later attempts get strictly less time.
    Only :class:`InferenceServerException` is ever retried; breaker
    open-state failures raise without consuming retry attempts.
    """
    start = clock()
    attempt = 0
    while True:
        if breaker is not None:
            try:
                # Outside the retry net: open circuits fail fast
                # instead of burning attempts — but the shed call IS a
                # client-visible unrecovered failure, so count it.
                breaker.before_call()
            except InferenceServerException as e:
                _note_if_exhausted(policy, e)
                raise
        remaining = None
        if deadline_s is not None:
            remaining = deadline_s - (clock() - start)
            if remaining <= 0:
                raise InferenceServerException(
                    "deadline of %.3fs exhausted after %d attempt(s)"
                    % (deadline_s, attempt), status="DEADLINE_EXCEEDED")
        try:
            result = fn(remaining)
        except InferenceServerException as e:
            if breaker is not None:
                _breaker_resolve(breaker, e)
            delay = None if policy is None else _next_delay(
                policy, e, attempt, deadline_s, clock() - start)
            if delay is None or (breaker is not None
                                 and not breaker.admits()):
                # No retry coming (attempts/budget spent, or the
                # breaker just opened): raise the REAL error now —
                # sleeping first and counting a phantom retry would
                # only delay the failure and skew the chaos report.
                _note_if_exhausted(policy, e)
                raise
            note_retries()
            sleep(delay)
            attempt += 1
            continue
        except BaseException as e:
            # Unexpected failures (decode bugs, KeyboardInterrupt,
            # cancellation) are never retried, but they MUST still
            # settle the breaker — an unresolved half-open probe locks
            # the client out.
            if breaker is not None:
                _breaker_resolve(breaker, e)
            raise
        if breaker is not None:
            breaker.record_success()
        return result


async def call_with_retry_async(
    fn,
    policy: Optional[RetryPolicy] = None,
    breaker: Optional[CircuitBreaker] = None,
    deadline_s: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
):
    """asyncio mirror of :func:`call_with_retry`; ``fn`` is an async
    callable taking the remaining-timeout budget."""
    import asyncio

    start = clock()
    attempt = 0
    while True:
        if breaker is not None:
            try:
                breaker.before_call()
            except InferenceServerException as e:
                # A shed call is a client-visible unrecovered failure.
                _note_if_exhausted(policy, e)
                raise
        remaining = None
        if deadline_s is not None:
            remaining = deadline_s - (clock() - start)
            if remaining <= 0:
                raise InferenceServerException(
                    "deadline of %.3fs exhausted after %d attempt(s)"
                    % (deadline_s, attempt), status="DEADLINE_EXCEEDED")
        try:
            result = await fn(remaining)
        except InferenceServerException as e:
            if breaker is not None:
                _breaker_resolve(breaker, e)
            delay = None if policy is None else _next_delay(
                policy, e, attempt, deadline_s, clock() - start)
            if delay is None or (breaker is not None
                                 and not breaker.admits()):
                # See the sync executor: never sleep toward an attempt
                # the breaker will refuse.
                _note_if_exhausted(policy, e)
                raise
            note_retries()
            await asyncio.sleep(delay)
            attempt += 1
            continue
        except BaseException as e:
            # See the sync executor: every failure (incl. task
            # cancellation) settles the breaker.
            if breaker is not None:
                _breaker_resolve(breaker, e)
            raise
        if breaker is not None:
            breaker.record_success()
        return result
