"""Mesh-slice serving (docs/sharded_serving.md): shard-mesh spec
parsing and slice planning, the sharded ReplicaSet (disjoint device
blocks, per-slice fault domains, chaos ``device=<id>`` kill ->
whole-slice ejection + readmission), slice-unit HBM admission
rollback, golden parity single-device vs tp-sharded LLMs across
dtypes (bf16 included), sharded paged-KV accounting (page-axis
rounding, per-member leases, zero leaks after cancel AND crash), mixed
sharded+unsharded traffic through one core, and the ensemble interior
arena landing (PR-16 follow-up: stage hand-offs become
pull-addressable regions instead of plain leases)."""

import json
import threading
import time

import numpy as np
import pytest

from client_tpu._infer_common import InferInput
from client_tpu.grpc._utils import get_inference_request
from client_tpu.models.ensemble import DataflowContext, EnsembleModel
from client_tpu.models.llm import LlmConfig, LlmModel
from client_tpu.server import chaos
from client_tpu.server import devstats as devstats_mod
from client_tpu.server import hbm as hbm_mod
from client_tpu.server import mesh as mesh_mod
from client_tpu.server.app import build_core
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.server.replicas import ReplicaSet
from client_tpu.utils import InferenceServerException

TINY = LlmConfig(vocab=264, d_model=64, n_layers=2, n_heads=4,
                 n_kv_heads=2, d_ff=128, max_seq=64)


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.configure(None)
    yield
    chaos.configure(None)


def _wait_for(predicate, timeout_s=10.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


# -- spec parsing / slice planning -----------------------------------------


def test_parse_shard_mesh_variants():
    assert mesh_mod.parse_shard_mesh({"tp": 4}) == [("tp", 4)]
    assert mesh_mod.parse_shard_mesh("sp=2,tp=2") \
        == [("sp", 2), ("tp", 2)]
    assert mesh_mod.parse_shard_mesh([("tp", 2), ("dp", 1)]) \
        == [("tp", 2)]  # size<=1 axes shard nothing and drop out
    assert mesh_mod.parse_shard_mesh(None) == []
    assert mesh_mod.parse_shard_mesh("") == []
    with pytest.raises(ValueError):
        mesh_mod.parse_shard_mesh("tp4")


def test_slice_width_and_wants_mesh():
    class _M:
        shard_mesh = {"sp": 2, "tp": 2}

    assert mesh_mod.wants_mesh(_M())
    assert mesh_mod.slice_width(_M()) == 4
    assert not mesh_mod.wants_mesh(object())
    assert mesh_mod.slice_width(object()) == 1


def test_plan_slice_contiguous_blocks_and_wrap():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, "conftest should provide 8 CPU devices"
    s0 = mesh_mod.plan_slice([("tp", 4)], 0)
    s1 = mesh_mod.plan_slice([("tp", 4)], 1)
    assert s0.device_ids == (0, 1, 2, 3)
    assert s1.device_ids == (4, 5, 6, 7)
    assert not set(s0.device_ids) & set(s1.device_ids)
    # Replica indexes are never reused; index 2 wraps onto block 0.
    assert mesh_mod.plan_slice([("tp", 4)], 2).device_ids \
        == s0.device_ids
    assert dict(s0.mesh.shape) == {"tp": 4}
    with pytest.raises(ValueError):
        mesh_mod.plan_slice([("tp", len(devices) * 2)], 0)


# -- sharded ReplicaSet ----------------------------------------------------


class _MeshStub(ServedModel):
    """Sharded-factory stub: records the mesh it was built over and
    computes OUTPUT = INPUT * 2 + 1 (slice-independent, so golden
    parity across slices is exact)."""

    instance_group_count = 2
    shard_mesh = {"tp": 2}

    def __init__(self, name="mesh_stub", mesh=None):
        super().__init__()
        self.name = name
        self.mesh = mesh
        self.inputs = [TensorSpec("INPUT", "INT32", [1])]
        self.outputs = [TensorSpec("OUTPUT", "INT32", [1])]

    def infer(self, inputs, parameters=None):
        value = np.asarray(inputs["INPUT"], dtype=np.int64)
        return {"OUTPUT": (value * 2 + 1).astype(np.int32)}


def _sharded_set(count=2, **kwargs):
    instances = []

    def factory(mesh=None):
        instance = _MeshStub(mesh=mesh)
        instances.append(instance)
        return instance

    base = _MeshStub()
    replica_set = ReplicaSet(base, factory=factory, count=count,
                             watchdog_us=2_000_000,
                             failure_threshold=2, recovery_s=0.2,
                             **kwargs)
    return replica_set, instances


def _one(value):
    return {"INPUT": np.array([value], dtype=np.int32)}


def test_sharded_set_builds_disjoint_slices():
    replica_set, instances = _sharded_set()
    try:
        snap = replica_set.snapshot()
        assert snap["sharded"] and snap["slice_width"] == 2
        blocks = [tuple(row["devices"]) for row in snap["replicas"]]
        assert blocks == [(0, 1), (2, 3)]
        # Every replica (index 0 included) is a fresh sharded
        # instance built over exactly its slice's mesh.
        assert len(instances) == 2
        for instance, block in zip(instances, blocks):
            assert instance.mesh is not None
            assert tuple(d.id for d in instance.mesh.devices.flat) \
                == block
        out = replica_set.infer(_one(5))
        assert int(np.asarray(out["OUTPUT"]).reshape(-1)[0]) == 11
    finally:
        replica_set.stop()


def test_sharded_set_degrades_without_factory(caplog):
    base = _MeshStub()
    replica_set = ReplicaSet(base, factory=None, count=2,
                             recovery_s=0.2)
    try:
        snap = replica_set.snapshot()
        assert not snap["sharded"] and snap["slice_width"] == 1
    finally:
        replica_set.stop()


def test_chaos_device_kill_ejects_whole_slice_and_readmits():
    """A single sick chip (chaos ``device=<id>``) must: (a) stay
    masked — the sibling slice serves every request; (b) eject exactly
    the slice containing the chip, with per-member device evidence;
    (c) readmit the slice once the chip heals."""
    replica_set, _ = _sharded_set()
    try:
        chaos.configure(chaos.ChaosConfig(error_rate=1.0, device=1))
        for value in range(6):
            out = replica_set.infer(_one(value))
            assert int(np.asarray(out["OUTPUT"]).reshape(-1)[0]) \
                == value * 2 + 1
        assert _wait_for(
            lambda: replica_set.snapshot()["healthy"] == 1)
        snap = replica_set.snapshot()
        sick = [row for row in snap["replicas"] if not row["healthy"]]
        assert len(sick) == 1 and sick[0]["devices"] == [0, 1]
        # Evidence names every member chip of the failed executions.
        assert snap["device_evidence"].get("CPU-0", 0) >= 1
        assert snap["device_evidence"].get("CPU-1", 0) >= 1
        chaos.configure(None)  # chip healed
        assert _wait_for(
            lambda: replica_set.snapshot()["healthy"] == 2)
        assert replica_set.snapshot()["readmissions"] >= 1
    finally:
        replica_set.stop()


def test_chaos_device_targeting_skips_untouched_slices():
    chaos.configure(chaos.ChaosConfig(error_rate=1.0, device=7))
    # Request layer (no devices): never fires.
    chaos.inject("m")
    # A slice not containing device 7: never fires.
    chaos.inject("m", replica_id="m:0", device_ids=(0, 1))
    with pytest.raises(InferenceServerException):
        chaos.inject("m", replica_id="m:1", device_ids=(6, 7))


# -- slice-unit HBM admission ----------------------------------------------


def test_admit_slice_rolls_back_partial_grants(monkeypatch):
    """A member device refusing its share must unwind every sibling
    grant — a failed slice admission leaves zero phantom pressure."""

    class _Weights:
        def __init__(self):
            self.weights = np.zeros(1024, dtype=np.float32)  # 4 KiB

    allocator = hbm_mod.HbmAllocator(
        budget_bytes=3000,
        stats=devstats_mod.DeviceStats(enabled=True))
    monkeypatch.setattr(hbm_mod, "_SINGLETON", allocator)
    # CPU-1 is nearly full: its 2 KiB share cannot fit, CPU-0's can.
    blocker = allocator.lease("blocker", "weights", 2800,
                              device_key="CPU-1")
    assert blocker is not None
    mesh_slice = mesh_mod.plan_slice([("tp", 2)], 0)
    with pytest.raises(InferenceServerException):
        mesh_mod.admit_slice("victim", mesh_slice, _Weights())
    assert not allocator._by_model.get("victim")


def test_admit_slice_books_per_device_rows(monkeypatch):
    class _Weights:
        def __init__(self):
            self.weights = np.zeros(1024, dtype=np.float32)

    allocator = hbm_mod.HbmAllocator(
        budget_bytes=1 << 20,
        stats=devstats_mod.DeviceStats(enabled=True))
    monkeypatch.setattr(hbm_mod, "_SINGLETON", allocator)
    mesh_slice = mesh_mod.plan_slice([("tp", 2)], 0)
    resources = mesh_mod.admit_slice("m", mesh_slice, _Weights())
    leases = list(resources.leases)
    assert sorted(lease.device_key for lease in leases) \
        == ["CPU-0", "CPU-1"]
    assert all(lease.nbytes == 2048 for lease in leases)
    resources.release()
    resources.release()  # idempotent
    assert not allocator._by_model.get("m")


# -- sharded LLM: golden parity + sharded paged KV -------------------------


def _gen(model, prompt, n=6, ignore_eos=True):
    return [t for t in model._generate(
        {"text_input": np.array([prompt], dtype=np.object_),
         "max_tokens": np.array([n], dtype=np.int32),
         "ignore_eos": np.array([ignore_eos])}, {})]


def _drain(model, timeout_s=30.0):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        snap = model.kv_stats()
        if not (snap["pages_used"] or snap["pages_reserved"]
                or model._active):
            return snap
        time.sleep(0.05)
    return model.kv_stats()


def _tp2_mesh():
    import jax

    from client_tpu.parallel import create_mesh

    return create_mesh((("tp", 2),), devices=jax.devices()[:2])


@pytest.mark.parametrize("dtype", ["bfloat16", "float32"])
def test_llm_sharded_golden_parity_across_dtypes(dtype):
    """tp=2 sharded serving is byte-identical to the single-device
    model — greedy decode over the page-axis-sharded KV pool must not
    perturb a single logit, in bf16 or fp32."""
    cfg = LlmConfig(vocab=264, d_model=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=128, max_seq=64, dtype=dtype)
    single = LlmModel(name="llm_one_%s" % dtype, cfg=cfg,
                      decode_lanes=2, page_size=4)
    sharded = LlmModel(name="llm_tp2_%s" % dtype, cfg=cfg,
                       mesh=_tp2_mesh(), decode_lanes=2, page_size=4)
    try:
        assert sharded._paged, "sharded LLM must serve the paged arm"
        for prompt in (b"abc", b"sharded parity probe " * 2):
            assert _gen(single, prompt, 8) == _gen(sharded, prompt, 8)
    finally:
        single.unload()
        sharded.unload()


def test_llm_sharded_kv_pool_rounds_and_leases_per_member():
    model = LlmModel(name="llm_kv_shard", cfg=TINY, mesh=_tp2_mesh(),
                     decode_lanes=2, page_size=4, kv_pages=9)
    try:
        assert len(_gen(model, b"warm", 4)) == 4
        # Page axis shards over tp=2: the count rounds UP to a
        # shard-count multiple and each member holds a sub-pool.
        assert model._num_pages == 10
        leases = list(model._kv_leases)
        assert sorted(lease.device_key for lease in leases) \
            == ["CPU-0", "CPU-1"]
        assert {lease.component for lease in leases} \
            == {"kv_pages:CPU-0", "kv_pages:CPU-1"}
        snap = _drain(model)
        assert snap["pages_used"] == 0 and snap["pages_reserved"] == 0
    finally:
        model.unload()


def test_llm_sharded_kv_leak_free_after_cancel_and_crash():
    """The PR-19 cancel/crash matrix against the sharded pool: an
    abandoned stream and an injected device failure must both return
    the sharded pool to zero pages (no per-member sub-pool may strand
    a page)."""
    model = LlmModel(name="llm_kv_churn", cfg=TINY, mesh=_tp2_mesh(),
                     decode_lanes=2, page_size=4)
    try:
        # Cancel mid-stream.
        gen = model._generate(
            {"text_input": np.array([b"abandon sharded stream"],
                                    dtype=np.object_),
             "max_tokens": np.array([50], dtype=np.int32),
             "ignore_eos": np.array([True])}, {})
        next(gen)
        assert model.kv_stats()["pages_used"] > 0
        gen.close()
        snap = _drain(model)
        assert snap["pages_used"] == 0 and snap["pages_reserved"] == 0
        # Crash mid-decode: generation bump rebuilds the SHARDED pool.
        real = model._paged_decode
        state = {"armed": True}

        def exploding(*args, **kwargs):
            if state["armed"]:
                state["armed"] = False
                raise RuntimeError("injected device failure")
            return real(*args, **kwargs)

        model._paged_decode = exploding
        with pytest.raises(InferenceServerException, match="failed"):
            _gen(model, b"boom", 8)
        model._paged_decode = real
        assert len(_gen(model, b"after", 4)) == 4
        snap = _drain(model)
        assert snap["pages_used"] == 0 and snap["pages_reserved"] == 0
    finally:
        model.unload()


# -- mixed sharded + unsharded traffic through one core --------------------


def test_mixed_sharded_and_unsharded_traffic_one_core():
    """A mesh-sharded instance group and a plain host model serve
    concurrently from one core: the sharded set's slices and the
    unsharded model's direct path must not disturb each other."""
    core = build_core([], warmup=False)
    name = "mesh_mixed"
    try:
        core.repository.add_factory(
            name, lambda mesh=None: _MeshStub(name=name, mesh=mesh))
        core.load_model(name, warmup=False)
        core.load_model("simple", warmup=False)

        def _mesh_request(value):
            tensor = InferInput("INPUT", [1], "INT32")
            tensor.set_data_from_numpy(
                np.array([value], dtype=np.int32))
            return get_inference_request(model_name=name,
                                         inputs=[tensor], outputs=None)

        def _simple_request(value):
            tensors = []
            for tname, fill in (("INPUT0", value), ("INPUT1", 2 * value)):
                tensor = InferInput(tname, [16], "INT32")
                tensor.set_data_from_numpy(
                    np.full((16,), fill, dtype=np.int32))
                tensors.append(tensor)
            return get_inference_request(model_name="simple",
                                         inputs=tensors, outputs=None)

        # First sharded request builds the ReplicaSet lazily; its
        # debug snapshot must then report slice serving.
        response = core.infer(_mesh_request(3))
        out = np.frombuffer(response.raw_output_contents[0],
                            dtype=np.int32)
        assert int(out[0]) == 7
        snap = core.debug_snapshot()["replicas"][name]
        assert snap["sharded"] and snap["slice_width"] == 2

        errors = []

        def worker(kind, value):
            try:
                if kind == "sharded":
                    response = core.infer(_mesh_request(value))
                    out = np.frombuffer(
                        response.raw_output_contents[0], dtype=np.int32)
                    assert int(out[0]) == value * 2 + 1, out
                else:
                    core.infer(_simple_request(value))
            except Exception as e:  # noqa: BLE001
                errors.append((kind, value, e))

        threads = [
            threading.Thread(target=worker,
                             args=("sharded" if i % 2 else "plain", i))
            for i in range(12)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        # The sharded model renders its per-slice health gauge.
        assert 'tpu_slice_healthy{model="%s",slice="0"} 1' % name \
            in core.metrics_text()
    finally:
        core.shutdown()


# -- ensemble interior tensors land in arena regions -----------------------


class _FakeDeviceArray:
    """Mimics an OFF-HOST jax array. CPU-sim jax arrays are host-
    committed (zero-copy to numpy), so the interior hand-off
    accounting correctly skips them — exercising the landing path
    needs an array whose devices() reports a non-cpu platform."""

    def __init__(self, data):
        self._data = np.asarray(data, dtype=np.float32)
        self.dtype = self._data.dtype
        self.shape = self._data.shape
        self.nbytes = self._data.nbytes

    def __array__(self, dtype=None):
        return self._data if dtype is None \
            else self._data.astype(dtype)

    def devices(self):
        class _Device:
            platform = "tpu"

        return {_Device()}


class _DeviceMid(ServedModel):
    """Stage whose output stays 'device-resident' into the next
    stage."""

    max_batch_size = 0

    def __init__(self, name="arena_mid"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("XIN", "FP32", [4])]
        self.outputs = [TensorSpec("H", "FP32", [4])]

    def infer(self, inputs, parameters=None):
        x = np.asarray(inputs["XIN"], dtype=np.float32)
        return {"H": _FakeDeviceArray(x * 2.0)}


class _HostTail(ServedModel):
    max_batch_size = 0

    def __init__(self, name="arena_tail"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("H", "FP32", [4])]
        self.outputs = [TensorSpec("OUT", "FP32", [1])]

    def infer(self, inputs, parameters=None):
        x = np.asarray(inputs["H"], dtype=np.float32)
        return {"OUT": x.sum(axis=-1, keepdims=True)}


class _MiniRepo:
    def __init__(self, models):
        self._models = {m.name: m for m in models}

    def load(self, name):
        return self._models[name]


def _interior_ensemble():
    repo = _MiniRepo([_DeviceMid(), _HostTail()])
    return EnsembleModel(
        name="arena_ens",
        repository=repo,
        steps=[
            ("arena_mid", {"XIN": "XIN"}, {"h": "H"}),
            ("arena_tail", {"h": "H"}, {"OUT": "OUT"}),
        ],
        inputs=[TensorSpec("XIN", "FP32", [4])],
        outputs=[TensorSpec("OUT", "FP32", [1])],
    )


def test_land_interior_adopts_typed_segments():
    core = build_core([], warmup=False)
    try:
        arena = core.memory.arena
        if arena is None:
            pytest.skip("no arena on this platform")
        outputs = {"H": _FakeDeviceArray(np.arange(4.0)),
                   "Z": _FakeDeviceArray(np.arange(8.0))}
        nbytes = sum(v.nbytes for v in outputs.values())
        region_id = EnsembleModel._land_interior(arena, outputs, nbytes)
        assert region_id is not None
        segments = arena.snapshot_segments(region_id)
        assert len(segments) == 2
        assert [seg.offset for seg in segments] == [0, 16]
        assert all(seg.datatype == "FP32" for seg in segments)
        arena.destroy_region(region_id)
    finally:
        core.shutdown()


def test_ensemble_interior_lands_in_arena_and_cleans_up():
    """Each interior stage boundary lands one arena region (the
    pull-addressable zero-copy edge) and every region dies with the
    request — the arena holds no interior residue afterwards."""
    core = build_core([], warmup=False)
    try:
        arena = core.memory.arena
        if arena is None:
            pytest.skip("no arena on this platform")
        ensemble = _interior_ensemble()
        baseline = len(arena.list_regions())
        ctx = DataflowContext(arena=arena)
        outputs, _queue_ns = ensemble.infer_dataflow(
            {"XIN": np.arange(4, dtype=np.float32)}, {}, ctx)
        assert float(np.asarray(outputs["OUT"]).reshape(-1)[0]) \
            == pytest.approx(12.0)  # sum(2 * [0..3])
        assert ensemble.interior_arena_regions == 1
        assert len(arena.list_regions()) == baseline
        # Without an arena the site falls back to the interior lease
        # path (best-effort) and still serves identically.
        outputs, _ = ensemble.infer_dataflow(
            {"XIN": np.arange(4, dtype=np.float32)}, {},
            DataflowContext())
        assert float(np.asarray(outputs["OUT"]).reshape(-1)[0]) \
            == pytest.approx(12.0)
        assert ensemble.interior_arena_regions == 1  # unchanged
    finally:
        core.shutdown()
