"""Ring attention vs dense attention on the virtual 8-device CPU mesh
(conftest forces JAX_PLATFORMS=cpu with 8 host devices): the ring
rotation + streaming softmax must be EXACT (up to float tolerance)
against single-device softmax attention for causal and full
attention, with and without a data-parallel axis."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from client_tpu.parallel import create_mesh  # noqa: E402
from client_tpu.parallel.ring_attention import ring_attention  # noqa: E402


def dense_attention(q, k, v, causal):
    b, s, h, d = q.shape
    logits = jnp.einsum("bshd,bthd->bhst",
                        q.astype(jnp.float32), k.astype(jnp.float32))
    logits = logits / (d ** 0.5)
    if causal:
        mask = np.tril(np.ones((s, s), bool))
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bthd->bshd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)


def _rand_qkv(b=2, s=64, h=4, d=16, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    shape = (b, s, h, d)
    return tuple(rng.standard_normal(shape).astype(dtype)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense_sp8(causal):
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = create_mesh((("sp", 8),))
    q, k, v = _rand_qkv()
    out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, causal=causal)
    expected = dense_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_ring_with_dp_axis():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = create_mesh((("dp", 2), ("sp", 4)))
    q, k, v = _rand_qkv(b=4, s=32)
    out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, causal=True)
    expected = dense_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_ring_bf16_and_jit():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = create_mesh((("sp", 8),))
    q, k, v = _rand_qkv(dtype=np.float32, s=32)
    q = jnp.asarray(q, jnp.bfloat16)
    k = jnp.asarray(k, jnp.bfloat16)
    v = jnp.asarray(v, jnp.bfloat16)
    fn = jax.jit(lambda a, b2, c: ring_attention(a, b2, c, mesh,
                                                 causal=True))
    out = fn(q, k, v)
    assert out.dtype == jnp.bfloat16
    expected = dense_attention(q, k, v, True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expected, np.float32),
        rtol=5e-2, atol=5e-2)


def test_llm_forward_with_ring_attention_matches_dense():
    """End-to-end: the LLM scoring forward with ring attention over an
    sp=8 mesh produces the same logits as the dense single-path
    forward (context parallelism is a layout change, not a model
    change)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from client_tpu.models.llm import (
        LlmConfig,
        forward,
        init_params,
        ring_attention_fn,
    )

    cfg = LlmConfig(d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                    d_ff=128, max_seq=64, dtype="float32")
    params = init_params(jax.random.PRNGKey(1), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab, (2, 32)),
        jnp.int32)
    mesh = create_mesh((("sp", 8),))
    dense = forward(params, tokens, cfg)
    ring = forward(params, tokens, cfg,
                   attention_fn=ring_attention_fn(mesh))
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-3, atol=2e-3)


def test_ring_outlier_masked_logit_no_nan():
    """A future (masked) key strongly aligned with an early query must
    not poison the streaming softmax: the exp is gated by the mask, so
    an outlier masked logit can't overflow to inf*0=NaN."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = create_mesh((("sp", 8),))
    q, k, v = _rand_qkv(b=1, s=16, h=2, d=8, seed=3)
    q[0, 0] = 40.0   # query at position 0 ...
    k[0, 15] = 40.0  # ... aligned with a masked future key
    out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, causal=True)
    assert np.isfinite(np.asarray(out)).all()
    expected = dense_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)
