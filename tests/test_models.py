"""Model zoo tests (small configs, CPU) incl. decoupled LLM streaming
through the real gRPC stream — the first decoupled end-to-end
exercise."""

import queue

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
from client_tpu.models.bert import BertConfig, BertModel
from client_tpu.models.ensemble import (
    PostprocessModel,
    PreprocessModel,
    make_image_ensemble,
)
from client_tpu.models.llm import ByteTokenizer, LlmConfig, LlmModel
from client_tpu.models.resnet import ResNetConfig, ResNetModel
from client_tpu.server.app import build_core, start_grpc_server


TINY_LLM = LlmConfig(d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                     d_ff=128, max_seq=128)
TINY_BERT = BertConfig(vocab=1000, d_model=64, n_layers=2, n_heads=4,
                       d_ff=128, max_seq=128)


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    ids = tok.encode("hello é")
    assert ids[0] == 256  # BOS
    assert tok.decode(ids) == "hello é"


def test_llm_generate_stream_direct():
    model = LlmModel(name="llm_test", cfg=TINY_LLM)
    pieces = list(model.infer_stream({
        "text_input": np.array([b"abc"], dtype=np.object_),
        "max_tokens": np.array([5], dtype=np.int32),
        "ignore_eos": np.array([True]),
    }))
    assert 1 <= len(pieces) <= 5
    for piece in pieces:
        assert piece["text_output"].dtype == np.object_


def test_llm_generate_deterministic():
    model = LlmModel(name="llm_test", cfg=TINY_LLM)
    run1 = model.infer({
        "text_input": np.array([b"abc"], dtype=np.object_),
        "max_tokens": np.array([4], dtype=np.int32),
        "ignore_eos": np.array([True]),
    })
    run2 = model.infer({
        "text_input": np.array([b"abc"], dtype=np.object_),
        "max_tokens": np.array([4], dtype=np.int32),
        "ignore_eos": np.array([True]),
    })
    assert run1["text_output"][0] == run2["text_output"][0]


def test_llm_concurrent_generations_batched_lanes():
    """Multiple concurrent generations ride separate decode lanes and
    must each produce exactly what a solo run produces (greedy decode
    is lane-independent: per-lane masks and cache slices)."""
    import threading

    model = LlmModel(name="llm_test", cfg=TINY_LLM, decode_lanes=3)

    def run(prompt):
        return [t for t in model._generate(
            {"text_input": np.array([prompt], dtype=np.object_),
             "max_tokens": np.array([6], dtype=np.int32),
             "ignore_eos": np.array([True])}, {})]

    prompts = [b"alpha", b"beta", b"gamma", b"delta", b"epsilon"]
    solo = {p: run(p) for p in prompts}

    results = {}
    errors = []

    def worker(p):
        try:
            results[p] = run(p)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(p,)) for p in prompts]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    for p in prompts:
        assert results[p] == solo[p], p


def test_llm_abandoned_stream_releases_lane():
    """Closing the generator mid-stream (client disconnect) must free
    the decode lane at the next chunk instead of decoding the full
    budget into an unread queue."""
    import time

    model = LlmModel(name="llm_test", cfg=TINY_LLM, decode_lanes=1)
    gen = model._generate(
        {"text_input": np.array([b"abandon me"], dtype=np.object_),
         "max_tokens": np.array([500], dtype=np.int32),
         "ignore_eos": np.array([True])}, {})
    next(gen)   # request is live on the only lane
    gen.close()  # consumer walks away
    deadline = time.time() + 30
    while time.time() < deadline and model._active:
        time.sleep(0.05)
    assert not model._active
    # the lane is reusable: a fresh request completes
    out = list(model._generate(
        {"text_input": np.array([b"next"], dtype=np.object_),
         "max_tokens": np.array([4], dtype=np.int32),
         "ignore_eos": np.array([True])}, {}))
    assert len(out) == 4


def test_llm_pipeline_churn_with_random_cancels():
    """Stress the dispatch/delivery pipeline: more concurrent
    generations than lanes, a fraction abandoned mid-stream — every
    surviving request must produce its solo-run tokens and every
    request must terminate (no lane leak, no hang)."""
    import random
    import threading
    import time

    model = LlmModel(name="llm_churn", cfg=TINY_LLM, decode_lanes=2)
    rng = random.Random(7)

    def run_full(prompt):
        return [t for t in model._generate(
            {"text_input": np.array([prompt], dtype=np.object_),
             "max_tokens": np.array([5], dtype=np.int32),
             "ignore_eos": np.array([True])}, {})]

    prompts = [("p%d" % i).encode() for i in range(8)]
    # Reference outputs only for prompts that are never in the cancel
    # set (workers cancel index % 3 == 2).
    reference = [prompts[0], prompts[1], prompts[3]]
    solo = {p: run_full(p) for p in reference}

    results, errors = {}, []

    def worker(index, prompt):
        try:
            gen = model._generate(
                {"text_input": np.array([prompt], dtype=np.object_),
                 "max_tokens": np.array([5], dtype=np.int32),
                 "ignore_eos": np.array([True])}, {})
            if index % 3 == 2:  # abandon after the first token
                next(gen)
                gen.close()
                results[prompt] = "cancelled"
            else:
                results[prompt] = list(gen)
        except Exception as e:  # noqa: BLE001
            errors.append((prompt, e))

    for round_idx in range(3):
        threads = [
            threading.Thread(target=worker, args=(i, p))
            for i, p in enumerate(prompts)
        ]
        rng.shuffle(threads)
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "a generation hung"
        assert not errors, errors
        for p in reference:
            assert results[p] == solo[p], (round_idx, p)
        # pipeline fully drained between rounds
        deadline = time.time() + 30
        while time.time() < deadline and model._active:
            time.sleep(0.05)
        assert not model._active
        assert sorted(model._free_lanes) == [0, 1]


def test_llm_pipeline_crash_recovery():
    """A device failure mid-decode must fail every rider loudly (no
    client blocks forever) and the next request must restart the
    pipeline cleanly (generation bump, fresh lanes)."""
    model = LlmModel(name="llm_crash", cfg=TINY_LLM, decode_lanes=2)

    # Prime (compiles + proves the happy path), then arm a one-shot
    # failure inside the decode dispatch.
    ok = list(model._generate(
        {"text_input": np.array([b"prime"], dtype=np.object_),
         "max_tokens": np.array([4], dtype=np.int32),
         "ignore_eos": np.array([True])}, {}))
    assert len(ok) == 4

    # Drain the prime request's pipeline fully before arming the
    # failure — a stale in-flight dispatch could otherwise consume it.
    import time

    deadline = time.time() + 30
    while time.time() < deadline and (
            model._active or model._inflight or
            sorted(model._free_lanes) != [0, 1]):
        time.sleep(0.05)
    assert sorted(model._free_lanes) == [0, 1]

    # Patch whichever decode kernel the configured arm dispatches
    # (paged by default; _decode_chunk_multi on the dense A/B arm).
    attr = "_paged_decode" if model._paged else "_decode_chunk_multi"
    real_decode = getattr(model, attr)
    state = {"armed": True}

    def exploding(*args, **kwargs):
        if state["armed"]:
            state["armed"] = False
            raise RuntimeError("injected device failure")
        return real_decode(*args, **kwargs)

    setattr(model, attr, exploding)
    from client_tpu.utils import InferenceServerException

    with pytest.raises(InferenceServerException, match="failed"):
        list(model._generate(
            {"text_input": np.array([b"boom"], dtype=np.object_),
             "max_tokens": np.array([8], dtype=np.int32),
             "ignore_eos": np.array([True])}, {}))

    # Recovery: pipeline restarted (new generation), request completes.
    out = list(model._generate(
        {"text_input": np.array([b"after"], dtype=np.object_),
         "max_tokens": np.array([4], dtype=np.int32),
         "ignore_eos": np.array([True])}, {}))
    assert len(out) == 4
    # Lane release runs on the delivery thread AFTER the terminating
    # None is consumed — drain before asserting, like the churn test.
    import time

    deadline = time.time() + 30
    while time.time() < deadline and sorted(model._free_lanes) != [0, 1]:
        time.sleep(0.05)
    assert sorted(model._free_lanes) == [0, 1]


def test_llm_chunked_decode_matches_single_step():
    """decode_chunk (device-side lax.scan loop, one fetch per chunk)
    must reproduce the per-token decode_step sequence exactly —
    chunking changes the host round-trip count, never the tokens."""
    import jax
    import jax.numpy as jnp

    from client_tpu.models.llm import decode_chunk, decode_step, init_cache

    model = LlmModel(name="llm_test", cfg=TINY_LLM)
    params, cfg = model._params, model.cfg
    prompt = jnp.full((1, 4), 7, dtype=jnp.int32)
    from client_tpu.models.llm import prefill

    logits, cache_a = prefill(params, prompt, init_cache(cfg, 1), cfg,
                              true_len=4)
    cache_b = jax.tree.map(jnp.copy, cache_a)
    first = jnp.argmax(logits[0]).astype(jnp.int32)

    chunk, _ = decode_chunk(params, first, 4, cache_a, cfg, length=6)
    singles = []
    token, pos = first, 4
    for _ in range(6):
        step_logits, cache_b = decode_step(
            params, token.reshape(1, 1), pos, cache_b, cfg)
        token = jnp.argmax(step_logits[0]).astype(jnp.int32)
        singles.append(int(token))
        pos += 1
    assert [int(t) for t in np.asarray(chunk)] == singles


@pytest.mark.slow  # compiles the full resnet50 forward on CPU
def test_resnet_forward_shapes():
    model = ResNetModel(cfg=ResNetConfig(width=16, num_classes=10))
    out = model.infer({"INPUT": np.zeros((2, 224, 224, 3), np.float32)})
    assert np.asarray(out["OUTPUT"]).shape == (2, 10)
    # unbatched input gets a batch dim
    out = model.infer({"INPUT": np.zeros((224, 224, 3), np.float32)})
    assert np.asarray(out["OUTPUT"]).shape == (1, 10)


def test_bert_bucketing_and_mask():
    model = BertModel(cfg=TINY_BERT)
    ids = np.arange(10, dtype=np.int32) % 1000
    out1 = model.infer({"input_ids": ids})
    assert np.asarray(out1["logits"]).shape == (1, 2)
    # same tokens padded by the bucketing must give the same logits
    ids_padded = np.concatenate([ids, np.zeros(5, np.int32)])
    mask = np.concatenate([np.ones(10, np.int32), np.zeros(5, np.int32)])
    out2 = model.infer({"input_ids": ids_padded, "attention_mask": mask})
    np.testing.assert_allclose(
        np.asarray(out1["logits"]), np.asarray(out2["logits"]),
        rtol=2e-2, atol=2e-2,
    )


def test_ensemble_pipeline():
    from client_tpu.server.repository import ModelRepository

    repo = ModelRepository()
    repo.add_model(PreprocessModel())
    repo.add_model(ResNetModel(cfg=ResNetConfig(width=16, num_classes=10)))
    repo.add_model(PostprocessModel(num_classes=10))
    ensemble = make_image_ensemble(repo)
    out = ensemble.infer({
        "RAW_IMAGE": np.zeros((224, 224, 3), np.uint8)
    })
    label = out["LABEL"]
    assert b":" in np.asarray(label).reshape(-1)[0]
    config = ensemble.config_pb()
    assert [s.model_name for s in config.ensemble_scheduling.step] == [
        "preprocess", "resnet50", "postprocess",
    ]


@pytest.fixture(scope="module")
def llm_server():
    core = build_core([])
    core.repository.add_model(LlmModel(name="llm_test", cfg=TINY_LLM),
                              warmup=True)
    handle = start_grpc_server(core=core)
    yield handle
    handle.stop()


def test_llm_decoupled_stream_over_grpc(llm_server):
    """BASELINE config #5 shape: decoupled token streaming over the
    bidi gRPC stream with final-response semantics."""
    results = queue.Queue()
    with grpcclient.InferenceServerClient(llm_server.address) as client:
        meta = client.get_model_metadata("llm_test")
        assert meta.name == "llm_test"
        config = client.get_model_config("llm_test")
        assert config.config.model_transaction_policy.decoupled

        client.start_stream(lambda r, e: results.put((r, e)))
        inputs = [
            grpcclient.InferInput("text_input", [1], "BYTES"),
            grpcclient.InferInput("max_tokens", [1], "INT32"),
            grpcclient.InferInput("ignore_eos", [1], "BOOL"),
        ]
        inputs[0].set_data_from_numpy(np.array([b"hello"], dtype=np.object_))
        inputs[1].set_data_from_numpy(np.array([4], dtype=np.int32))
        inputs[2].set_data_from_numpy(np.array([True]))
        client.async_stream_infer("llm_test", inputs, request_id="gen1",
                                  enable_empty_final_response=True)

        tokens = []
        while True:
            result, error = results.get(timeout=60)
            assert error is None, error
            params = result.get_parameters()
            if params.get("triton_final_response"):
                break
            out = result.as_numpy("text_output")
            if out is not None:
                tokens.append(out.reshape(-1)[0])
        client.stop_stream()
    assert 1 <= len(tokens) <= 4


def test_bert_truncates_beyond_max_seq():
    """Inputs longer than max_seq must be truncated, not crash —
    buckets are clamped to the configured max_seq."""
    model = BertModel(cfg=TINY_BERT)
    long_ids = np.ones((1, TINY_BERT.max_seq + 40), dtype=np.int32)
    out = model.infer({"input_ids": long_ids})
    assert out["logits"].shape[-1] == TINY_BERT.num_labels


def test_llm_prefill_bucketing_consistent():
    """Different prompt lengths hit the same padded prefill and still
    produce the same continuation as an unpadded run would."""
    model = LlmModel(name="llm_b", cfg=TINY_LLM)
    outs = []
    for text in ("hi", "hello there, long prompt " * 3):
        pieces = [r["text_output"] for r in model.infer_stream(
            {"text_input": np.array([text.encode()], dtype=np.object_),
             "max_tokens": np.array([4], dtype=np.int32)})]
        assert pieces
        outs.append(pieces)
