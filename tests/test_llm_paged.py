"""Paged KV cache + continuous batching (docs/llm_serving.md).

Golden parity paged-vs-dense (batched, chunked, prefix-hit prefill;
join/leave mid-stream), copy-on-write prefix sharing, page-exhaustion
admission control (bounded wait -> completion, deadline expiry,
watermark shed with Retry-After), and pool accounting returning to
zero after cancel and forced crash-recovery."""

import threading
import time

import numpy as np
import pytest

from client_tpu.models.llm import (
    LlmConfig,
    LlmModel,
    _PagePool,
    prefix_page_hashes,
)
from client_tpu.utils import InferenceServerException

TINY = LlmConfig(d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                 d_ff=128, max_seq=128)


def _gen(model, prompt, n=6, timeout_us=None, ignore_eos=True):
    params = {} if timeout_us is None else {"timeout": timeout_us}
    return [t for t in model._generate(
        {"text_input": np.array([prompt], dtype=np.object_),
         "max_tokens": np.array([n], dtype=np.int32),
         "ignore_eos": np.array([ignore_eos])}, params)]


def _drain(model, timeout_s=30.0):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        snap = model.kv_stats()
        if snap is None:
            if not model._active:
                return None
        elif not (snap["pages_used"] or snap["pages_reserved"]
                  or model._active):
            return snap
        time.sleep(0.05)
    return model.kv_stats()


@pytest.fixture(scope="module")
def arms():
    dense = LlmModel(name="llm_pd", cfg=TINY, paged_kv=False,
                     decode_lanes=2)
    paged = LlmModel(name="llm_pp", cfg=TINY, paged_kv=True,
                     decode_lanes=3, page_size=4)
    yield dense, paged
    dense.unload()
    paged.unload()


# -- parity ----------------------------------------------------------------


def test_paged_parity_batched_and_chunked_prefill(arms):
    """Token-exact vs dense across both prefill routes: short prompts
    (batched scratch prefill + page pack) and prompts longer than
    prefill_chunk (bounded chunked prefill)."""
    dense, paged = arms
    for prompt in (b"abc", b"a much longer prompt for the chunked "
                          b"prefill route to split " * 2):
        assert _gen(dense, prompt, 8) == _gen(paged, prompt, 8), prompt


def test_paged_parity_join_leave_mid_stream(arms):
    """More concurrent generations than lanes, staggered joins and
    leaves: every request must produce exactly its solo-run tokens
    (greedy decode is lane-independent under block-table gather)."""
    dense, paged = arms
    prompts = [("join leave %d" % i).encode() for i in range(7)]
    solo = {p: _gen(paged, p) for p in prompts}
    results, errors = {}, []

    def worker(p, delay):
        try:
            time.sleep(delay)
            results[p] = _gen(paged, p)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(p, 0.03 * i))
               for i, p in enumerate(prompts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    for p in prompts:
        assert results[p] == solo[p] == _gen(dense, p), p


def test_prefix_sharing_cow_divergence(arms):
    """Two prompts sharing a long system prefix: the second join must
    hit the prefix cache (pages reused, not recomputed) and still
    produce exactly its dense-arm tokens — divergence after the
    shared prefix lands in private (copy-on-write) pages."""
    dense, paged = arms
    sys_prompt = b"shared system prompt padding: " * 2
    first = _gen(paged, sys_prompt + b"tail one")
    hits0 = paged.kv_stats()["prefix_hits_total"]
    second = _gen(paged, sys_prompt + b"completely different tail two")
    hits1 = paged.kv_stats()["prefix_hits_total"]
    assert hits1 > hits0, "second join did not reuse prefix pages"
    assert first == _gen(dense, sys_prompt + b"tail one")
    assert second == _gen(
        dense, sys_prompt + b"completely different tail two")


def test_eos_parity_without_ignore(arms):
    """EOS handling (device-side done latch on the paged arm) must
    terminate streams at the same token as the dense arm."""
    dense, paged = arms
    for prompt in (b"eos parity", b"x"):
        assert _gen(dense, prompt, 20, ignore_eos=False) \
            == _gen(paged, prompt, 20, ignore_eos=False)


# -- admission control -----------------------------------------------------


def test_exhaustion_bounded_wait_then_completion():
    """A join that cannot reserve pages waits in the join queue and
    completes once the holder's pages free — no failure, no leak."""
    model = LlmModel(name="llm_wait", cfg=TINY, paged_kv=True,
                     decode_lanes=2, page_size=4, kv_pages=12,
                     queue_timeout_s=60.0)
    results = {}

    def run(tag, prompt):
        results[tag] = _gen(model, prompt, 16)

    # Each request needs ~ceil((prompt + 15)/4) pages; two of these
    # cannot reserve 12 pages simultaneously.
    t1 = threading.Thread(target=run,
                          args=("a", b"first big request padd xx"))
    t2 = threading.Thread(target=run,
                          args=("b", b"second big request padd yy"))
    t1.start()
    t2.start()
    t1.join(120)
    t2.join(120)
    assert len(results["a"]) == 16 and len(results["b"]) == 16
    snap = _drain(model)
    assert snap["pages_used"] == 0 and snap["pages_reserved"] == 0
    model.unload()


def test_exhaustion_deadline_and_watermark_shed():
    """Behind a pool-holding stream: a queued join dies on its PR-2
    queue deadline (DEADLINE_EXCEEDED), and past the watermark new
    arrivals shed immediately with RESOURCE_EXHAUSTED + an honest
    Retry-After estimate."""
    model = LlmModel(name="llm_shed", cfg=TINY, paged_kv=True,
                     decode_lanes=2, page_size=4, kv_pages=24,
                     join_watermark=1, queue_timeout_s=30.0)
    hold = model._generate(
        {"text_input": np.array([b"hold most of the pool here"],
                                dtype=np.object_),
         "max_tokens": np.array([60], dtype=np.int32),
         "ignore_eos": np.array([True])}, {})
    next(hold)
    with pytest.raises(InferenceServerException) as excinfo:
        _gen(model, b"needs pages that never free", 60,
             timeout_us=300000)
    assert excinfo.value.status() == "DEADLINE_EXCEEDED"

    queued = threading.Thread(
        target=lambda: _try(model, b"queued forever request", 60))
    queued.start()
    time.sleep(0.3)  # let it reach the join queue (watermark = 1)
    with pytest.raises(InferenceServerException) as excinfo:
        _gen(model, b"shed at the door", 60)
    assert excinfo.value.status() == "RESOURCE_EXHAUSTED"
    assert getattr(excinfo.value, "retry_after_s", 0) > 0
    assert model.kv_stats()["shed_total"] >= 1
    hold.close()
    queued.join(120)
    snap = _drain(model)
    assert snap["pages_used"] == 0 and snap["pages_reserved"] == 0
    model.unload()


def _try(model, prompt, n):
    try:
        _gen(model, prompt, n)
    except InferenceServerException:
        pass


def test_cancelled_holder_admits_queued_join():
    """Cancelling a pool-holding stream must count as scheduler
    progress: the freed pages admit the queued join promptly instead
    of letting it sleep to its deadline (review regression)."""
    model = LlmModel(name="llm_reap", cfg=TINY, paged_kv=True,
                     decode_lanes=2, page_size=4, kv_pages=24,
                     queue_timeout_s=60.0)
    hold = model._generate(
        {"text_input": np.array([b"hold most of the pool here"],
                                dtype=np.object_),
         "max_tokens": np.array([60], dtype=np.int32),
         "ignore_eos": np.array([True])}, {})
    next(hold)
    done = threading.Event()
    results = {}

    def queued():
        results["tokens"] = _gen(model, b"queued join waits for pages",
                                 60)
        done.set()

    thread = threading.Thread(target=queued)
    thread.start()
    time.sleep(0.5)  # reaches the join queue, cannot reserve
    hold.close()
    assert done.wait(25.0), "queued join did not admit after cancel"
    assert len(results["tokens"]) == 60
    thread.join(30)
    _drain(model)
    model.unload()


def test_timeout_zero_keeps_default_deadline():
    """`timeout=0` means 'no per-request override' (PR-2 batcher
    semantics), not a zero-microsecond deadline: a queued join with
    timeout=0 must survive the wait, not die instantly."""
    model = LlmModel(name="llm_t0", cfg=TINY, paged_kv=True,
                     decode_lanes=2, page_size=4, kv_pages=24,
                     queue_timeout_s=60.0)
    hold = model._generate(
        {"text_input": np.array([b"hold most of the pool here"],
                                dtype=np.object_),
         "max_tokens": np.array([60], dtype=np.int32),
         "ignore_eos": np.array([True])}, {})
    next(hold)
    outcome = {}

    def queued():
        try:
            outcome["tokens"] = _gen(
                model, b"zero timeout join padd", 60, timeout_us=0)
        except InferenceServerException as e:
            outcome["error"] = e

    thread = threading.Thread(target=queued)
    thread.start()
    time.sleep(1.0)
    assert "error" not in outcome, outcome.get("error")
    hold.close()
    thread.join(60)
    assert outcome.get("tokens"), outcome
    _drain(model)
    model.unload()


def test_oversized_request_rejected_immediately():
    model = LlmModel(name="llm_big", cfg=TINY, paged_kv=True,
                     decode_lanes=2, page_size=4, kv_pages=8)
    with pytest.raises(InferenceServerException) as excinfo:
        _gen(model, b"x" * 200, 120)
    assert excinfo.value.status() == "INVALID_ARGUMENT"
    model.unload()


# -- pool accounting -------------------------------------------------------


def test_cancel_mid_stream_frees_pages():
    model = LlmModel(name="llm_cancel", cfg=TINY, paged_kv=True,
                     decode_lanes=2, page_size=4)
    gen = model._generate(
        {"text_input": np.array([b"abandon this stream"],
                                dtype=np.object_),
         "max_tokens": np.array([100], dtype=np.int32),
         "ignore_eos": np.array([True])}, {})
    next(gen)
    assert model.kv_stats()["pages_used"] > 0
    gen.close()
    snap = _drain(model)
    assert snap["pages_used"] == 0 and snap["pages_reserved"] == 0
    # lane is reusable afterwards
    assert len(_gen(model, b"next", 4)) == 4
    model.unload()


def test_crash_recovery_does_not_leak_pages():
    """A device failure mid-decode fails every rider loudly; the
    generation bump rebuilds the pool with zero pages held and the
    next request completes."""
    model = LlmModel(name="llm_crash2", cfg=TINY, paged_kv=True,
                     decode_lanes=2, page_size=4)
    assert len(_gen(model, b"prime", 4)) == 4
    _drain(model)
    real = model._paged_decode
    state = {"armed": True}

    def exploding(*args, **kwargs):
        if state["armed"]:
            state["armed"] = False
            raise RuntimeError("injected device failure")
        return real(*args, **kwargs)

    model._paged_decode = exploding
    with pytest.raises(InferenceServerException, match="failed"):
        _gen(model, b"boom", 8)
    model._paged_decode = real
    snap = model.kv_stats()
    assert snap["pages_used"] == 0 and snap["pages_reserved"] == 0
    assert len(_gen(model, b"after", 4)) == 4
    snap = _drain(model)
    assert snap["pages_used"] == 0 and snap["pages_reserved"] == 0
    model.unload()


def test_budget_limits_page_allocation():
    """Run-ahead never allocates pages past the request's token
    budget: a 3-token request on a fresh pool touches only the pages
    its prompt + 2 decode slots need, not STREAM_CHUNK's worth."""
    model = LlmModel(name="llm_budget", cfg=TINY, paged_kv=True,
                     decode_lanes=1, page_size=4)
    prompt = b"abcdefg"  # 8 tokens with BOS
    _gen(model, prompt, 3)
    snap = _drain(model)
    # 8 prompt tokens + 2 decode slots = 10 slots -> 3 pages of 4.
    assert snap["pages_used_peak"] <= 3
    model.unload()


# -- page pool unit --------------------------------------------------------


def test_page_pool_reservation_invariant():
    pool = _PagePool(num_pages=8, page_size=4)
    assert pool.can_admit(8, 0)
    assert not pool.can_admit(9, 0)
    pool.reserve(6)
    pages = pool.alloc(6)
    assert len(pages) == 6 and pool.reserved == 0
    assert not pool.can_admit(3, 0)
    with pytest.raises(RuntimeError):
        pool.alloc(1)  # nothing reserved
    pool.free(pages)
    assert pool.snapshot()["pages_used"] == 0
    assert pool.snapshot()["pages_free"] == 8


def test_page_pool_prefix_lifecycle_and_eviction():
    pool = _PagePool(num_pages=4, page_size=4)
    hashes = prefix_page_hashes(np.arange(8, dtype=np.int32), 4)
    assert len(hashes) == 2
    pool.reserve(2)
    pages = pool.alloc(2)
    for digest, page in zip(hashes, pages):
        pool.register(digest, page)
    assert pool.shared_live == 2
    # a second lane attaches: still the same physical pages
    hits, pinned = pool.peek_chain(hashes, 2)
    assert (hits, pinned) == (2, 0)
    attached = pool.attach(hashes)
    assert attached == pages
    pool.free(attached)
    pool.free(pages)
    snap = pool.snapshot()
    assert snap["pages_used"] == 0 and snap["pages_cached"] == 2
    # cache-only pages are evictable: a fresh reservation can claim
    # the whole pool
    pool.reserve(4)
    fresh = pool.alloc(4)
    assert len(fresh) == 4
    assert pool.snapshot()["pages_cached"] == 0


def test_prefix_hash_is_chained():
    """Page 1's hash must depend on page 0's tokens (K/V depend on
    the whole prefix through attention)."""
    a = prefix_page_hashes(np.array([1, 2, 3, 4, 5, 6, 7, 8]), 4)
    b = prefix_page_hashes(np.array([9, 2, 3, 4, 5, 6, 7, 8]), 4)
    assert a[0] != b[0]
    assert a[1] != b[1]  # same page-1 tokens, different prefix


# -- metrics ---------------------------------------------------------------


def test_kv_metric_families_on_metrics_endpoint():
    from client_tpu.server.app import build_core

    core = build_core([])
    model = LlmModel(name="llm_kv_metrics", cfg=TINY, paged_kv=True,
                     decode_lanes=2, page_size=4)
    core.repository.add_model(model)
    _gen(model, b"metrics please", 4)
    text = core.metrics_text()
    for family in ("tpu_kv_pages_used", "tpu_kv_pages_total",
                   "tpu_kv_prefix_hits_total",
                   "tpu_prefill_chunks_total"):
        assert '%s{model="llm_kv_metrics"}' % family in text, family
    core.shutdown()


def test_dense_arm_reports_no_kv_stats(arms):
    dense, paged = arms
    assert dense.kv_stats() is None
    assert paged.kv_stats()["pages_total"] > 0
