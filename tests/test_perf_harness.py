"""perf harness unit tests over the mock backend (tier-1 strategy of
SURVEY.md §4 — no server required) plus a short in-process CLI e2e."""

import json
import os
import time

import numpy as np
import pytest

from client_tpu.perf.client_backend import (
    BackendKind,
    ClientBackendFactory,
    MockBackend,
)
from client_tpu.perf.data_loader import DataLoader
from client_tpu.perf.load_manager import (
    ConcurrencyManager,
    FifoCtxIdTracker,
    InferDataManager,
    RandCtxIdTracker,
    RequestRateManager,
    SequenceManager,
)
from client_tpu.perf.model_parser import ModelParser, SchedulerType
from client_tpu.perf.profiler import InferenceProfiler, MeasurementConfig
from client_tpu.utils import InferenceServerException


def make_mock_setup(delay_s=0.001, stats=None):
    factory = ClientBackendFactory(BackendKind.MOCK, mock_delay_s=delay_s,
                                   mock_stats=stats)
    backend = factory.create()
    model = ModelParser().parse(backend, "mock")
    loader = DataLoader(model)
    loader.generate_data()
    data_manager = InferDataManager(model, loader)
    return factory, model, loader, data_manager


# -- ctx id trackers -------------------------------------------------------


def test_fifo_ctx_tracker():
    tracker = FifoCtxIdTracker()
    tracker.reset(3)
    assert [tracker.get() for _ in range(3)] == [0, 1, 2]
    assert not tracker.available()
    assert tracker.get(timeout=0.01) is None
    tracker.release(1)
    assert tracker.get() == 1


def test_rand_ctx_tracker():
    tracker = RandCtxIdTracker()
    tracker.reset(5)
    got = {tracker.get() for _ in range(5)}
    assert got == {0, 1, 2, 3, 4}


# -- model parser ----------------------------------------------------------


def test_model_parser_basic():
    backend = MockBackend()
    model = ModelParser().parse(backend, "mock")
    assert model.name == "mock"
    assert "INPUT0" in model.inputs
    assert model.scheduler_type == SchedulerType.NONE
    assert not model.decoupled


def test_model_parser_batch_rejection():
    backend = MockBackend()
    with pytest.raises(InferenceServerException, match="does not support"):
        ModelParser().parse(backend, "mock", batch_size=4)


def test_shape_tensor_stays_unbatched():
    """A config input marked is_shape_tensor keeps its unbatched shape
    and single data copy at batch>1 (reference
    ModelTensor.is_shape_tensor, model_parser.h:41)."""
    backend = MockBackend(
        model_metadata_dict={
            "name": "m", "versions": ["1"], "platform": "mock",
            "inputs": [
                {"name": "INPUT0", "datatype": "FP32", "shape": [16]},
                {"name": "INPUT1", "datatype": "INT32", "shape": [2]},
            ],
            "outputs": [
                {"name": "OUTPUT0", "datatype": "FP32", "shape": [16]},
            ],
        },
        model_config_dict={
            "name": "m", "max_batch_size": 8,
            "input": [{"name": "INPUT1", "is_shape_tensor": True}],
        }
    )
    model = ModelParser().parse(backend, "m", batch_size=4)
    assert not model.inputs["INPUT0"].is_shape_tensor
    assert model.inputs["INPUT1"].is_shape_tensor

    loader = DataLoader(model)
    loader.generate_data()
    manager = InferDataManager(model, loader, batch_size=4)
    inputs = manager.build_inputs()
    by_name = {i.name(): i for i in inputs}
    assert by_name["INPUT0"].shape()[0] == 4  # leading batch dim
    assert by_name["INPUT1"].shape() == model.inputs["INPUT1"].shape
    assert len(by_name["INPUT0"].raw_data()) == 4 * 16 * 4  # replicated
    assert len(by_name["INPUT1"].raw_data()) == 2 * 4  # single copy


def test_model_parser_ensemble_sequence_kind():
    """An ensemble with a sequence-batched composing model refines to
    ENSEMBLE_SEQUENCE (reference model_parser.h:63)."""
    backend = MockBackend(
        model_configs={
            "top": {"name": "top",
                    "ensemble_scheduling": {"step": [{"model_name": "leaf"}]}},
            "leaf": {"name": "leaf", "sequence_batching": {}},
        }
    )
    model = ModelParser().parse(backend, "top")
    assert model.scheduler_type == SchedulerType.ENSEMBLE_SEQUENCE
    assert model.composing_sequential


def test_model_parser_scheduler_kinds():
    backend = MockBackend(
        model_config_dict={"name": "m", "max_batch_size": 8,
                           "dynamic_batching": {}}
    )
    model = ModelParser().parse(backend, "m")
    assert model.scheduler_type == SchedulerType.DYNAMIC
    backend = MockBackend(
        model_config_dict={
            "name": "m",
            "ensemble_scheduling": {"step": [{"model_name": "a"},
                                             {"model_name": "b"}]},
        }
    )
    model = ModelParser().parse(backend, "m")
    assert model.scheduler_type == SchedulerType.ENSEMBLE
    assert model.composing_models == ["a", "b"]
    backend = MockBackend(
        model_config_dict={
            "name": "m",
            "model_transaction_policy": {"decoupled": True},
        }
    )
    assert ModelParser().parse(backend, "m").decoupled


def test_model_parser_recursive_composing():
    """Ensemble steps that are themselves ensembles resolve
    recursively; sequence-batched children flip composing_sequential
    (reference DetermineComposingModelMap/GetComposingSchedulerType)."""
    backend = MockBackend(
        model_config_dict={
            "name": "top",
            "ensemble_scheduling": {"step": [{"model_name": "mid"}]},
        },
        model_configs={
            "mid": {"ensemble_scheduling":
                    {"step": [{"model_name": "leaf"}]}},
            "leaf": {"sequence_batching": {}},
        },
    )
    model = ModelParser().parse(backend, "top")
    assert model.composing_models == ["mid", "leaf"]
    assert model.composing_sequential


def test_model_parser_bls_composing_and_cache():
    backend = MockBackend(
        model_config_dict={"name": "bls",
                           "response_cache": {"enable": True}},
        model_configs={"callee": {"max_batch_size": 4}},
    )
    model = ModelParser().parse(
        backend, "bls", bls_composing_models=["callee", "callee"])
    assert model.composing_models == ["callee"]  # deduped
    assert model.response_cache_enabled


# -- data loader -----------------------------------------------------------


def test_data_loader_random_and_zero():
    backend = MockBackend()
    model = ModelParser().parse(backend, "mock")
    loader = DataLoader(model)
    loader.generate_data()
    data = loader.get_input_data("INPUT0")
    assert data.shape == [16]
    assert data.array.dtype == np.float32
    loader.generate_data(zero_input=True)
    assert not loader.get_input_data("INPUT0").array.any()


def test_data_loader_json():
    backend = MockBackend()
    model = ModelParser().parse(backend, "mock")
    loader = DataLoader(model)
    loader.read_data_from_json({
        "data": [
            {"INPUT0": [float(i) for i in range(16)]},
            {"INPUT0": {"content": [1.0] * 16, "shape": [16]}},
        ]
    })
    assert loader.step_count(0) == 2
    np.testing.assert_array_equal(
        loader.get_input_data("INPUT0", 0, 0).array,
        np.arange(16, dtype=np.float32),
    )


def test_data_loader_json_b64_and_streams():
    import base64

    backend = MockBackend()
    model = ModelParser().parse(backend, "mock")
    loader = DataLoader(model)
    raw = np.arange(16, dtype=np.float32)
    loader.read_data_from_json({
        "data": [
            [{"INPUT0": {"b64": base64.b64encode(raw.tobytes()).decode(),
                          "shape": [16]}}],
            [{"INPUT0": [0.5] * 16}],
        ]
    })
    assert loader.stream_count == 2
    np.testing.assert_array_equal(loader.get_input_data("INPUT0", 0, 0).array,
                                  raw)


def test_data_loader_validation_errors():
    backend = MockBackend()
    model = ModelParser().parse(backend, "mock")
    loader = DataLoader(model)
    with pytest.raises(InferenceServerException, match="missing data"):
        loader.read_data_from_json({"data": [{}]})
    with pytest.raises(InferenceServerException, match="incompatible"):
        loader.read_data_from_json(
            {"data": [{"INPUT0": {"content": [1.0] * 4, "shape": [4]}}]}
        )
    with pytest.raises(InferenceServerException, match="not a model input"):
        loader.read_data_from_json({"data": [{"NOPE": [1.0]}]})


# -- sequence manager ------------------------------------------------------


def test_sequence_manager_lifecycle():
    manager = SequenceManager(start_id=100, sequence_length=3,
                              sequence_length_variation=0.0)
    state = manager.new_sequence()
    k1 = manager.advance(state)
    assert k1 == {"sequence_id": 100, "sequence_start": True,
                  "sequence_end": False}
    k2 = manager.advance(state)
    assert not k2["sequence_start"] and not k2["sequence_end"]
    k3 = manager.advance(state)
    assert k3["sequence_end"]


def test_sequence_manager_id_range():
    manager = SequenceManager(start_id=10, id_range=2, sequence_length=1)
    ids = {manager.new_sequence()["id"] for _ in range(5)}
    assert ids == {10, 11}


# -- concurrency manager ---------------------------------------------------


def _concurrency_manager(factory, model, loader, data_manager, **kw):
    manager = ConcurrencyManager(
        factory=factory, model=model, data_loader=loader,
        data_manager=data_manager, **kw,
    )
    manager.init()
    return manager


def test_concurrency_manager_collects_records():
    stats = MockBackend.Stats()
    factory, model, loader, dm = make_mock_setup(0.002, stats)
    manager = _concurrency_manager(factory, model, loader, dm)
    manager.change_concurrency_level(4)
    time.sleep(0.3)
    records = manager.swap_request_records()
    manager.cleanup()
    assert len(records) > 20
    assert all(r.valid for r in records)
    assert stats.async_infer_calls > 20


def test_concurrency_manager_sync_mode():
    factory, model, loader, dm = make_mock_setup(0.001)
    manager = _concurrency_manager(factory, model, loader, dm,
                                   async_mode=False)
    manager.change_concurrency_level(2)
    time.sleep(0.2)
    records = manager.swap_request_records()
    manager.cleanup()
    assert len(records) > 10


def test_concurrency_manager_streaming():
    factory, model, loader, dm = make_mock_setup(0.001)
    manager = _concurrency_manager(factory, model, loader, dm, streaming=True)
    manager.change_concurrency_level(2)
    time.sleep(0.3)
    records = manager.swap_request_records()
    manager.cleanup()
    assert len(records) > 10
    assert all(r.valid for r in records)


def test_concurrency_level_change():
    factory, model, loader, dm = make_mock_setup(0.001)
    manager = _concurrency_manager(factory, model, loader, dm)
    manager.change_concurrency_level(1)
    time.sleep(0.15)
    low = len(manager.swap_request_records())
    manager.change_concurrency_level(8)
    time.sleep(0.15)
    high = len(manager.swap_request_records())
    manager.cleanup()
    assert high > low


def test_sequences_through_manager():
    stats = MockBackend.Stats()
    factory, model, loader, dm = make_mock_setup(0.001, stats)
    seq = SequenceManager(sequence_length=3, sequence_length_variation=0.0)
    manager = _concurrency_manager(factory, model, loader, dm,
                                   sequence_manager=seq)
    manager.change_concurrency_level(2)
    time.sleep(0.2)
    manager.cleanup()
    assert stats.sequence_ids, "sequence ids should be recorded"
    starts = [p for p in stats.request_parameters if p.get("sequence_start")]
    ends = [p for p in stats.request_parameters if p.get("sequence_end")]
    assert starts and ends


# -- request rate manager --------------------------------------------------


def test_request_rate_manager_rate():
    factory, model, loader, dm = make_mock_setup(0.0)
    manager = RequestRateManager(
        factory=factory, model=model, data_loader=loader, data_manager=dm,
    )
    manager.init()
    manager.change_request_rate(100.0)
    time.sleep(1.0)
    records = manager.swap_request_records()
    manager.cleanup()
    # ~100/s over 1s window, generous tolerance for CI noise
    assert 50 < len(records) < 160


def test_request_rate_poisson():
    factory, model, loader, dm = make_mock_setup(0.0)
    manager = RequestRateManager(
        factory=factory, model=model, data_loader=loader, data_manager=dm,
        distribution="poisson",
    )
    manager.init()
    manager.change_request_rate(200.0)
    time.sleep(0.5)
    records = manager.swap_request_records()
    manager.cleanup()
    assert len(records) > 40


def test_custom_intervals():
    factory, model, loader, dm = make_mock_setup(0.0)
    manager = RequestRateManager(
        factory=factory, model=model, data_loader=loader, data_manager=dm,
    )
    manager.init()
    manager.set_custom_schedule([0.01, 0.02])  # avg 15ms -> ~66/s
    time.sleep(0.6)
    records = manager.swap_request_records()
    manager.cleanup()
    assert 20 < len(records) < 80


# -- profiler --------------------------------------------------------------


def test_profiler_stability_and_merge():
    factory, model, loader, dm = make_mock_setup(0.002)
    manager = _concurrency_manager(factory, model, loader, dm)
    config = MeasurementConfig(
        measurement_interval_ms=150, max_trials=8, stability_threshold=0.5,
    )
    profiler = InferenceProfiler(manager, config)
    results = profiler.profile_concurrency_range(1, 2)
    manager.cleanup()
    assert len(results) == 2
    assert results[0].concurrency == 1
    assert results[1].concurrency == 2
    for status in results:
        assert status.completed_count > 0
        assert status.throughput > 0
        assert status.latency_percentiles[50] > 0
        assert 50 in status.latency_percentiles
        assert status.avg_latency_us >= 1000  # 2ms mock delay


def test_profiler_latency_threshold_stops_sweep():
    factory, model, loader, dm = make_mock_setup(0.01)
    manager = _concurrency_manager(factory, model, loader, dm)
    config = MeasurementConfig(
        measurement_interval_ms=100, max_trials=4, stability_threshold=0.9,
        latency_threshold_ms=0.001,  # everything exceeds
    )
    profiler = InferenceProfiler(manager, config)
    results = profiler.profile_concurrency_range(1, 8)
    manager.cleanup()
    assert len(results) == 1  # stopped after first level


def test_profiler_count_windows():
    factory, model, loader, dm = make_mock_setup(0.001)
    manager = _concurrency_manager(factory, model, loader, dm)
    config = MeasurementConfig(
        measurement_mode="count_windows", measurement_request_count=20,
        measurement_interval_ms=100, max_trials=4, stability_threshold=0.9,
    )
    profiler = InferenceProfiler(manager, config)
    results = profiler.profile_concurrency_range(2, 2)
    manager.cleanup()
    assert results[0].completed_count >= 20


def test_profiler_all_empty_windows_is_an_error():
    """A level whose every window completes zero requests must raise,
    not report zero stats (reference: inference_profiler.cc 'No valid
    requests recorded' error)."""
    factory, model, loader, dm = make_mock_setup(10.0)  # 10s delay
    manager = _concurrency_manager(factory, model, loader, dm)
    config = MeasurementConfig(
        measurement_interval_ms=40, max_trials=2, stability_threshold=0.5,
    )
    profiler = InferenceProfiler(manager, config)
    with pytest.raises(InferenceServerException,
                       match="no valid requests"):
        profiler.profile_concurrency_range(1, 1)
    manager.cleanup()


def test_profiler_server_stats_are_window_deltas():
    """server_stats must reflect only the measured windows, not the
    cumulative totals (the reference pairs start/end snapshots per
    Measure window): warmup traffic before profiling must not leak
    into the reported inference_count."""
    from client_tpu.perf.client_backend import (
        BackendKind,
        ClientBackendFactory,
    )
    from client_tpu.perf.data_loader import DataLoader
    from client_tpu.perf.load_manager import InferDataManager
    from client_tpu.perf.model_parser import ModelParser
    from client_tpu.server.app import build_core

    core = build_core(["simple"])
    factory = ClientBackendFactory(BackendKind.IN_PROCESS, core=core)
    backend = factory.create()
    # 50 warmup inferences that must NOT appear in the window delta.
    parsed = ModelParser().parse(backend, "simple", batch_size=1)
    loader = DataLoader(parsed)
    loader.generate_data()
    dm = InferDataManager(parsed, loader, batch_size=1)
    warm_manager = _concurrency_manager(factory, parsed, loader, dm)
    import numpy as np

    for _ in range(50):
        from client_tpu.protocol import inference_pb2 as pb

        req = pb.ModelInferRequest(model_name="simple")
        for name in ("INPUT0", "INPUT1"):
            t = req.inputs.add()
            t.name = name
            t.datatype = "INT32"
            t.shape.extend([16])
            req.raw_input_contents.append(
                np.zeros(16, dtype=np.int32).tobytes())
        core.infer(req)
    config = MeasurementConfig(
        measurement_interval_ms=200, max_trials=6, stability_threshold=0.9,
    )
    profiler = InferenceProfiler(
        warm_manager, config, backend, "simple")
    results = profiler.profile_concurrency_range(2, 2)
    warm_manager.cleanup()
    entry = results[0].server_stats["model_stats"][0]
    assert entry["name"] == "simple"
    # Delta, not cumulative: the window count tracks the requests the
    # profiler itself completed, excluding the 50 warmup inferences
    # and everything before the stable windows.
    window = entry["inference_count"]
    assert 0 < window, "no inferences recorded in window delta"
    total_stats = backend.model_statistics("simple")
    total = int(total_stats["model_stats"][0]["inference_count"])
    assert window <= total - 50, (
        "window delta %d should exclude the 50 warmup inferences "
        "(cumulative %d)" % (window, total))
    assert entry["inference_stats"]["success"]["count"] == window


def test_profiler_pairs_composing_model_stats():
    """Ensemble profiling reports per-window deltas for the composing
    models alongside the top model."""
    from client_tpu.perf.client_backend import (
        BackendKind,
        ClientBackendFactory,
    )
    from client_tpu.perf.data_loader import DataLoader
    from client_tpu.perf.load_manager import InferDataManager
    from client_tpu.perf.model_parser import ModelParser
    from client_tpu.server.app import build_core

    core = build_core(["ensemble_image"])
    factory = ClientBackendFactory(BackendKind.IN_PROCESS, core=core)
    backend = factory.create()
    parsed = ModelParser().parse(backend, "ensemble_image", batch_size=1)
    assert parsed.composing_models, "parser found no composing models"
    loader = DataLoader(parsed)
    loader.generate_data()
    dm = InferDataManager(parsed, loader, batch_size=1)
    manager = _concurrency_manager(factory, parsed, loader, dm)
    # count_windows: a contended box cannot close a window with zero
    # completions (which is an error since the reference-parity change).
    config = MeasurementConfig(
        measurement_mode="count_windows", measurement_request_count=4,
        measurement_interval_ms=500, max_trials=6, stability_threshold=0.9,
    )
    profiler = InferenceProfiler(
        manager, config, backend, "ensemble_image",
        composing_models=parsed.composing_models)
    results = profiler.profile_concurrency_range(2, 2)
    manager.cleanup()
    names = {e["name"] for e in results[0].server_stats["model_stats"]}
    assert "ensemble_image" in names
    for composing in parsed.composing_models:
        assert composing in names, (
            "composing model %s missing from %s" % (composing, names))


# -- CLI end-to-end (in-process) ------------------------------------------


def test_cli_inprocess_e2e(tmp_path):
    from client_tpu.perf.cli import run
    from client_tpu.server.app import build_core

    core = build_core(["simple"])
    csv_path = tmp_path / "report.csv"
    export_path = tmp_path / "profile.json"
    rc = run([
        "-m", "simple", "--service-kind", "inprocess",
        "--concurrency-range", "1:2",
        "--measurement-interval", "150", "--max-trials", "4",
        "--stability-percentage", "80",
        "-f", str(csv_path), "--profile-export-file", str(export_path),
    ], core=core)
    assert rc == 0
    assert csv_path.exists()
    doc = json.loads(export_path.read_text())
    assert doc["model"] == "simple"
    assert len(doc["experiments"]) == 2
    assert doc["experiments"][0]["requests"], "requests should be recorded"


def test_cli_request_count_single_window(tmp_path, capsys):
    """--request-count N measures exactly one fixed-count window
    (parity: the reference flag): N requests collected, no stability
    warning, single experiment."""
    from client_tpu.perf.cli import run
    from client_tpu.server.app import build_core

    core = build_core(["simple"])
    export_path = tmp_path / "profile.json"
    rc = run([
        "-m", "simple", "--service-kind", "inprocess",
        "--concurrency-range", "2",
        "--request-count", "20",
        "--measurement-interval", "2000",
        "--profile-export-file", str(export_path),
    ], core=core)
    out = capsys.readouterr().out
    assert rc == 0
    assert "did not stabilize" not in out, out
    doc = json.loads(export_path.read_text())
    assert len(doc["experiments"]) == 1
    assert len(doc["experiments"][0]["requests"]) >= 20


def test_cli_inprocess_shm_system(tmp_path):
    from client_tpu.perf.cli import run
    from client_tpu.server.app import build_core

    core = build_core(["simple"])
    rc = run([
        "-m", "simple", "--service-kind", "inprocess",
        "--concurrency-range", "1",
        "--shared-memory", "system",
        "--measurement-interval", "150", "--max-trials", "3",
        "--stability-percentage", "90",
    ], core=core)
    assert rc == 0


def test_cli_inprocess_shm_tpu(tmp_path):
    from client_tpu.perf.cli import run
    from client_tpu.server.app import build_core

    core = build_core(["simple"])
    rc = run([
        "-m", "simple", "--service-kind", "inprocess",
        "--concurrency-range", "1",
        "--shared-memory", "tpu",
        "--measurement-interval", "150", "--max-trials", "3",
        "--stability-percentage", "90",
    ], core=core)
    assert rc == 0


def test_cli_request_intervals_file(tmp_path):
    from client_tpu.perf.cli import run
    from client_tpu.server.app import build_core

    core = build_core(["simple"])
    intervals = tmp_path / "intervals.txt"
    intervals.write_text("5000\n10000\n5000\n")  # microseconds
    rc = run([
        "-m", "simple", "--service-kind", "inprocess",
        "--request-intervals", str(intervals),
        "--measurement-interval", "200", "--max-trials", "3",
        "--stability-percentage", "90",
    ], core=core)
    assert rc == 0


def test_cli_collect_metrics_against_http(tmp_path):
    """--collect-metrics scrapes the server's /metrics per window and
    the CSV grows the HBM columns."""
    from client_tpu.perf.cli import run
    from client_tpu.server.app import build_core
    from client_tpu.server.app import start_grpc_server
    from client_tpu.server.http_server import start_http_server_thread

    core = build_core(["simple"])
    grpc_handle = start_grpc_server(core=core)
    http_handle = start_http_server_thread(core, host="127.0.0.1", port=0)
    csv_path = tmp_path / "report.csv"
    try:
        rc = run([
            "-m", "simple", "-u", grpc_handle.address,
            "--concurrency-range", "1",
            "--collect-metrics",
            "--metrics-url", "http://127.0.0.1:%d/metrics" % http_handle.port,
            "--metrics-interval", "50",
            "--measurement-interval", "300", "--max-trials", "3",
            "--stability-percentage", "90",
            "-f", str(csv_path),
        ])
        assert rc == 0
        header = csv_path.read_text().splitlines()[0]
        assert "Avg HBM Used (MiB)" in header
    finally:
        http_handle.stop()
        grpc_handle.stop()


def test_data_loader_directory_input(tmp_path):
    """Directory-of-files input: one file per input (parity:
    reference DataLoader::ReadDataFromDir)."""
    from client_tpu.perf.model_parser import ModelTensor, ParsedModel

    model = ParsedModel()
    model.name = "m"
    model.inputs["INPUT0"] = ModelTensor("INPUT0", "FP32", [4])
    model.inputs["WORDS"] = ModelTensor("WORDS", "BYTES", [2])
    data = np.arange(4, dtype=np.float32)
    (tmp_path / "INPUT0").write_bytes(data.tobytes())
    (tmp_path / "WORDS").write_text("hello\nworld\n")
    loader = DataLoader(model)
    loader.read_data_from_dir(str(tmp_path))
    got = loader.get_input_data("INPUT0")
    np.testing.assert_array_equal(got.array, data)
    words = loader.get_input_data("WORDS")
    assert list(words.array) == [b"hello", b"world"]


def test_data_loader_directory_input_size_mismatch(tmp_path):
    from client_tpu.perf.model_parser import ModelTensor, ParsedModel
    from client_tpu.utils import InferenceServerException

    model = ParsedModel()
    model.name = "m"
    model.inputs["INPUT0"] = ModelTensor("INPUT0", "FP32", [4])
    (tmp_path / "INPUT0").write_bytes(b"\x00" * 7)  # not 16 bytes
    loader = DataLoader(model)
    with pytest.raises(InferenceServerException):
        loader.read_data_from_dir(str(tmp_path))


def test_native_perf_analyzer_directory_input(tmp_path):
    """Native harness accepts a directory for --input-data."""
    import pathlib
    import subprocess

    binary = pathlib.Path(__file__).resolve().parents[1] / "native" / \
        "build" / "perf_analyzer"
    if not binary.exists():
        pytest.skip("native perf_analyzer not built")
    # Serve the simple model and feed it from files.
    from client_tpu.server.app import build_core, start_grpc_server

    core = build_core(["simple"])
    handle = start_grpc_server(core=core)
    try:
        data = np.arange(16, dtype=np.int32)
        (tmp_path / "INPUT0").write_bytes(data.tobytes())
        (tmp_path / "INPUT1").write_bytes(data.tobytes())
        csv = tmp_path / "latency.csv"
        proc = subprocess.run(
            [str(binary), "-m", "simple", "-u", handle.address,
             "--input-data", str(tmp_path),
             "--concurrency-range", "1", "-p", "300", "-r", "3",
             "-s", "90", "-f", str(csv)],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert float(csv.read_text().splitlines()[1].split(",")[1]) > 0
    finally:
        handle.stop()
