"""Overlapped device->host output fetch (client_tpu.server.fetch):
golden parity against the legacy blocking-np.asarray path across
dtypes (incl. the bf16 bitcast), shapes, chunk boundaries, and fused
batch slices; fetch-into-region for shm-bound outputs; per-member
early completion; and error isolation (one output's failed fetch fails
only the members that requested it)."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from client_tpu.server.batcher import DynamicBatcher
from client_tpu.server.fetch import (
    DEFAULT_CHUNK_BYTES,
    OutputFetcher,
    fetch_into,
    host_committed,
    host_view,
    is_device_value,
)
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.utils import InferenceServerException


class FakeDeviceArray:
    """Array-like standing in for an off-host device tensor: host
    materialization (np.asarray) costs ``delay_s``, slicing yields a
    lazy sub-tensor (chunked transfers), and an optional error fires
    on materialization. Unlike a committed cpu jax.Array this never
    claims to be host-resident, so the fetcher routes it through the
    pool — which is exactly what the overlap tests need to observe."""

    def __init__(self, data: np.ndarray, delay_s: float = 0.0,
                 error: Exception = None):
        self._data = data
        self._delay_s = delay_s
        self._error = error
        self.shape = data.shape
        self.dtype = data.dtype
        self.nbytes = data.nbytes

    def __getitem__(self, item):
        return FakeDeviceArray(self._data[item], self._delay_s,
                               self._error)

    def __array__(self, dtype=None, copy=None):
        if self._delay_s:
            time.sleep(self._delay_s)
        if self._error is not None:
            raise self._error
        return self._data


def test_upload_tree_chunked_many_leaves_no_deadlock():
    """Regression: chunk-slice jobs used to be submitted to the SAME
    bounded pool their leaf job was blocking in — with every worker
    holding a chunkable leaf, the slices queued behind them could
    never run and the restore hung forever. The flat job plan uploads
    the same tree bit-identically with no job ever waiting on the
    pool it runs in."""
    from client_tpu.server.fetch import upload_tree

    leaves = {
        "w%d" % i: np.arange(i, i + 2048,
                             dtype=np.float32).reshape(8, 256)
        for i in range(6)
    }
    done = {}

    def run():
        # chunk_bytes=1024 makes every 8 KiB leaf split into 8 slice
        # jobs; workers=2 < chunkable-leaf count is the old hang.
        done["tree"] = upload_tree(dict(leaves), chunk_bytes=1024,
                                   workers=2)

    worker = threading.Thread(target=run, daemon=True)
    worker.start()
    worker.join(30.0)
    assert "tree" in done, "upload_tree deadlocked on nested submits"
    for name, host in leaves.items():
        assert np.array_equal(np.asarray(done["tree"][name]), host)


# -- primitives ------------------------------------------------------------


def test_is_device_value_and_host_committed():
    import jax.numpy as jnp

    host = np.arange(4, dtype=np.float32)
    dev = jnp.arange(4, dtype=jnp.float32)
    fake = FakeDeviceArray(host)
    assert not is_device_value(host)
    assert is_device_value(dev)
    assert is_device_value(fake)
    assert host_committed(host)
    # On the cpu backend jax arrays are committed host buffers.
    assert host_committed(dev)
    assert not host_committed(fake)


def test_host_view_is_single_copy():
    data = np.arange(64, dtype=np.float32)
    view = host_view(data)
    assert bytes(view) == data.tobytes()
    # The view aliases the materialized buffer — no tobytes copy.
    data[0] = -1.0
    assert np.frombuffer(view, np.float32)[0] == -1.0


@pytest.mark.parametrize("dtype", ["float32", "int32", "float16",
                                   "uint8", "bool"])
def test_fetch_into_parity_numeric(dtype):
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    host = (rng.random((33, 5)) * 100).astype(dtype)
    dev = jnp.asarray(host)
    golden = np.asarray(dev).tobytes()
    dest = bytearray(len(golden))
    written = fetch_into(dev, memoryview(dest))
    assert written == len(golden)
    assert bytes(dest) == golden


def test_fetch_into_parity_bf16_bitcast():
    import jax.numpy as jnp

    dev = jnp.arange(257, dtype=jnp.bfloat16) / 3
    golden = np.asarray(dev).tobytes()
    dest = bytearray(len(golden))
    fetch_into(dev, memoryview(dest))
    assert bytes(dest) == golden
    # Bitcast round trip: the landed bytes reinterpret to the same
    # bf16 values.
    import ml_dtypes

    landed = np.frombuffer(dest, dtype=ml_dtypes.bfloat16)
    np.testing.assert_array_equal(landed, np.asarray(dev))


def test_fetch_into_noncontiguous_source():
    base = np.arange(60, dtype=np.float32).reshape(6, 10)
    sliced = base[:, ::2]  # non-contiguous view
    golden = np.ascontiguousarray(sliced).tobytes()
    dest = bytearray(len(golden))
    fetch_into(sliced.copy(order="F"), memoryview(dest))
    assert bytes(dest) == golden


# -- OutputFetcher parity --------------------------------------------------


def test_fetcher_parity_across_dtypes_and_shapes():
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    outputs = {
        "fp32": jnp.asarray(rng.random((8, 16)).astype(np.float32)),
        "int32": jnp.asarray((rng.random(77) * 50).astype(np.int32)),
        "bf16": jnp.arange(1030, dtype=jnp.bfloat16) / 7,
        "bool": jnp.asarray(rng.random((3, 4, 5)) > 0.5),
        "host": rng.random(12).astype(np.float64),
    }
    fetcher = OutputFetcher(workers=2)
    try:
        inflight = fetcher.start(outputs)
        seen = {}
        for handle in inflight.as_completed():
            assert handle.error is None
            seen[handle.name] = handle.value
        assert set(seen) == set(outputs)
        for name, value in outputs.items():
            golden = value if isinstance(value, np.ndarray) \
                else np.asarray(value)
            np.testing.assert_array_equal(seen[name], golden)
            assert seen[name].dtype == golden.dtype
    finally:
        fetcher.shutdown()


def test_chunked_parity_and_odd_boundaries():
    """Chunked-parallel landing reassembles exactly, including when
    the row count does not divide by the chunk rows."""
    rng = np.random.default_rng(13)
    data = rng.random((37, 129)).astype(np.float32)  # odd everything
    fake = FakeDeviceArray(data)
    fetcher = OutputFetcher(workers=4, chunk_bytes=4096)
    try:
        inflight = fetcher.start({"OUT": fake})
        handle = next(inflight.as_completed())
        assert handle.error is None
        assert handle.chunks > 1  # it really chunked
        np.testing.assert_array_equal(handle.value, data)
    finally:
        fetcher.shutdown()


def test_chunking_skips_host_committed_arrays():
    """A committed cpu jax array's np.asarray is a zero-copy view;
    chunking it would add copies — the plan must land it whole,
    inline."""
    import jax.numpy as jnp

    big = jnp.zeros((64, 1024), dtype=jnp.float32)
    fetcher = OutputFetcher(workers=2, chunk_bytes=1024)
    try:
        inflight = fetcher.start({"OUT": big})
        handle = next(inflight.as_completed())
        assert handle.chunks == 0
        assert handle.value.shape == (64, 1024)
    finally:
        fetcher.shutdown()


def test_outputs_land_concurrently():
    """Two 150 ms transfers through the pool land in well under the
    serial 300 ms — the overlapped-copies property itself."""
    data = np.arange(32, dtype=np.float32)
    outputs = {
        "A": FakeDeviceArray(data, delay_s=0.15),
        "B": FakeDeviceArray(data * 2, delay_s=0.15),
    }
    fetcher = OutputFetcher(workers=4)
    try:
        start = time.monotonic()
        inflight = fetcher.start(outputs)
        inflight.wait()
        elapsed = time.monotonic() - start
        assert elapsed < 0.27, "transfers serialized (%.3fs)" % elapsed
        np.testing.assert_array_equal(inflight.result("A"), data)
        np.testing.assert_array_equal(inflight.result("B"), data * 2)
    finally:
        fetcher.shutdown()


def test_as_completed_yields_landing_order():
    data = np.arange(8, dtype=np.float32)
    outputs = {
        "slow": FakeDeviceArray(data, delay_s=0.3),
        "fast": FakeDeviceArray(data, delay_s=0.01),
    }
    fetcher = OutputFetcher(workers=2)
    try:
        order = [h.name for h in fetcher.start(outputs).as_completed()]
        assert order == ["fast", "slow"]
    finally:
        fetcher.shutdown()


def test_fetcher_error_rides_only_its_output():
    data = np.arange(8, dtype=np.float32)
    outputs = {
        "good": FakeDeviceArray(data),
        "bad": FakeDeviceArray(data, error=RuntimeError("dma fault")),
    }
    fetcher = OutputFetcher(workers=2)
    try:
        inflight = fetcher.start(outputs)
        np.testing.assert_array_equal(inflight.result("good"), data)
        with pytest.raises(RuntimeError, match="dma fault"):
            inflight.result("bad")
    finally:
        fetcher.shutdown()


# -- batcher integration ---------------------------------------------------


class _TwoOutModel(ServedModel):
    """Fusable model producing one fast and one slow fake-device
    output (rows = fused batch), for early-completion tests."""

    name = "two_out"
    max_batch_size = 8
    dynamic_batching = True

    def __init__(self, slow_s: float = 0.0, fail_slow: bool = False):
        super().__init__()
        self._slow_s = slow_s
        self._fail = fail_slow
        self.inputs = [TensorSpec("IN", "FP32", [4])]
        self.outputs = [TensorSpec("FAST", "FP32", [4]),
                        TensorSpec("SLOW", "FP32", [4])]

    def infer(self, inputs, parameters=None):
        array = np.asarray(inputs["IN"], dtype=np.float32)
        return {
            "FAST": FakeDeviceArray(array + 1.0, delay_s=0.01),
            "SLOW": FakeDeviceArray(
                array - 1.0, delay_s=self._slow_s,
                error=RuntimeError("slow output fetch died")
                if self._fail else None),
        }


def _member(batcher, value, wanted, results, key, timings=None):
    data = np.full((1, 4), value, dtype=np.float32)
    start = time.monotonic()
    try:
        outputs, _, _ = batcher.infer({"IN": data}, {}, 1,
                                      wanted_outputs=wanted)
        results[key] = outputs
    except Exception as e:  # noqa: BLE001 — asserted by the test
        results[key] = e
    if timings is not None:
        timings[key] = time.monotonic() - start


def test_member_early_completion_on_wanted_outputs():
    """A member that asked only for the fast output wakes as soon as
    it lands — while the fused batch's slow output is still in
    flight; a member wanting everything waits for both. Slices stay
    golden for both."""
    model = _TwoOutModel(slow_s=0.5)
    batcher = DynamicBatcher(model, max_queue_delay_us=100_000)
    results, timings = {}, {}
    threads = [
        threading.Thread(target=_member, args=(
            batcher, 5.0, frozenset(("FAST",)), results, "fast_only",
            timings)),
        threading.Thread(target=_member, args=(
            batcher, 9.0, None, results, "wants_all", timings)),
    ]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=30)
    batcher.stop()
    fast_only = results["fast_only"]
    wants_all = results["wants_all"]
    assert not isinstance(fast_only, Exception), fast_only
    assert not isinstance(wants_all, Exception), wants_all
    assert set(fast_only) == {"FAST"}
    assert set(wants_all) == {"FAST", "SLOW"}
    # Fused batch order is [fast_only, wants_all] or the reverse —
    # check values, not offsets.
    np.testing.assert_array_equal(fast_only["FAST"],
                                  np.full((1, 4), 6.0, np.float32))
    np.testing.assert_array_equal(wants_all["SLOW"],
                                  np.full((1, 4), 8.0, np.float32))
    assert timings["fast_only"] < timings["wants_all"], timings
    # 0.5 s of slow-output transfer never taxed the fast-only member.
    assert timings["wants_all"] - timings["fast_only"] > 0.2, timings


def test_failed_output_fetch_fails_only_requesters():
    """SLOW's fetch dies: the member that wanted only FAST still
    succeeds; the member wanting everything gets the INTERNAL error;
    the next batch is unaffected."""
    model = _TwoOutModel(slow_s=0.05, fail_slow=True)
    executions = []
    batcher = DynamicBatcher(
        model, max_queue_delay_us=100_000,
        stats_hook=lambda size, compute_ns, fetch_ns:
        executions.append(size))
    results = {}
    threads = [
        threading.Thread(target=_member, args=(
            batcher, 1.0, frozenset(("FAST",)), results, "fast_only")),
        threading.Thread(target=_member, args=(
            batcher, 2.0, None, results, "wants_all")),
        threading.Thread(target=_member, args=(
            batcher, 3.0, frozenset(("SLOW",)), results, "slow_only")),
    ]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=30)
    fast_only = results["fast_only"]
    assert not isinstance(fast_only, Exception), fast_only
    np.testing.assert_array_equal(fast_only["FAST"],
                                  np.full((1, 4), 2.0, np.float32))
    for key in ("wants_all", "slow_only"):
        error = results[key]
        assert isinstance(error, InferenceServerException), error
        assert "slow output fetch died" in str(error)
    # Error isolation across batches: the batcher still serves.
    model._fail = False
    late = {}
    _member(batcher, 7.0, None, late, "late")
    batcher.stop()
    assert not isinstance(late["late"], Exception), late["late"]
    np.testing.assert_array_equal(late["late"]["FAST"],
                                  np.full((1, 4), 8.0, np.float32))
    # The execution HAPPENED and served members — a partial fetch
    # failure must still record it (stats_hook per successful batch).
    assert len(executions) == 2, executions


def test_fused_slices_parity_mixed_batch_sizes():
    """Members of batch 1/2/1 get exactly their rows of the fused
    output — the scatter-offset contract under per-member wake."""
    class EchoModel(ServedModel):
        name = "echo"
        max_batch_size = 8
        dynamic_batching = True

        def infer(self, inputs, parameters=None):
            array = np.asarray(inputs["IN"], dtype=np.float32)
            return {"OUT": FakeDeviceArray(array * 10.0, delay_s=0.01)}

    batcher = DynamicBatcher(EchoModel(), max_queue_delay_us=150_000)
    results = {}

    def one(key, rows, value):
        data = np.full((rows, 4), value, dtype=np.float32)
        try:
            outputs, _, _ = batcher.infer({"IN": data}, {}, rows)
            results[key] = outputs["OUT"]
        except Exception as e:  # noqa: BLE001
            results[key] = e

    threads = [threading.Thread(target=one, args=(k, r, v))
               for k, r, v in (("a", 1, 1.0), ("b", 2, 2.0),
                               ("c", 1, 3.0))]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=30)
    batcher.stop()
    for key, rows, value in (("a", 1, 1.0), ("b", 2, 2.0),
                             ("c", 1, 3.0)):
        out = results[key]
        assert not isinstance(out, Exception), out
        np.testing.assert_array_equal(
            out, np.full((rows, 4), value * 10.0, np.float32))


def test_opt_out_keeps_legacy_serial_path():
    model = _TwoOutModel()
    model.overlapped_fetch = False
    batcher = DynamicBatcher(model, max_queue_delay_us=100_000,
                             overlapped_fetch=False)
    assert batcher._fetcher is None
    results = {}
    threads = [threading.Thread(target=_member, args=(
        batcher, float(i), None, results, i)) for i in range(2)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=30)
    batcher.stop()
    for i in range(2):
        out = results[i]
        assert not isinstance(out, Exception), out
        np.testing.assert_array_equal(
            out["FAST"], np.full((1, 4), i + 1.0, np.float32))


# -- shm / arena landing ---------------------------------------------------


def test_write_output_lands_device_tensor_in_region():
    """System-shm output placement routes device tensors through
    fetch_into — the region is the landing buffer, bytes match the
    legacy serialize path, bf16 included."""
    import jax.numpy as jnp

    from client_tpu.server.memory import SharedMemoryManager
    from client_tpu.utils import shared_memory as system_shm

    region = system_shm.create_shared_memory_region(
        "fetch_test", "/fetch_test_region", 1 << 16)
    manager = SharedMemoryManager()
    manager.register_system("fetch_test", "/fetch_test_region", 0,
                            1 << 16)
    try:
        for value in (jnp.arange(100, dtype=jnp.float32) * 0.5,
                      jnp.arange(100, dtype=jnp.bfloat16) / 3,
                      np.arange(100, dtype=np.int64)):
            golden = np.ascontiguousarray(np.asarray(value)).tobytes()
            written = manager.write_output("fetch_test", 1 << 16, 0,
                                           value)
            assert written == len(golden)
            landed = bytes(region.buf()[:written])
            assert landed == golden
    finally:
        manager.unregister_system("fetch_test")
        system_shm.destroy_shared_memory_region(region)


def test_write_output_bytes_tensor_keeps_serialize_path():
    from client_tpu.server.memory import SharedMemoryManager
    from client_tpu.utils import serialize_byte_tensor
    from client_tpu.utils import shared_memory as system_shm

    region = system_shm.create_shared_memory_region(
        "fetch_bytes", "/fetch_bytes_region", 4096)
    manager = SharedMemoryManager()
    manager.register_system("fetch_bytes", "/fetch_bytes_region", 0,
                            4096)
    try:
        value = np.array([b"alpha", b"bb", b"c" * 40], dtype=np.object_)
        golden = serialize_byte_tensor(value).tobytes()
        written = manager.write_output("fetch_bytes", 4096, 0, value)
        assert written == len(golden)
        assert bytes(region.buf()[:written]) == golden
    finally:
        manager.unregister_system("fetch_bytes")
        system_shm.destroy_shared_memory_region(region)


def test_write_output_bounds_still_enforced():
    from client_tpu.server.memory import SharedMemoryManager
    from client_tpu.utils import shared_memory as system_shm

    region = system_shm.create_shared_memory_region(
        "fetch_small", "/fetch_small_region", 64)
    manager = SharedMemoryManager()
    manager.register_system("fetch_small", "/fetch_small_region", 0, 64)
    try:
        too_big = np.arange(1024, dtype=np.float32)
        with pytest.raises(InferenceServerException):
            manager.write_output("fetch_small", 64, 0, too_big)
    finally:
        manager.unregister_system("fetch_small")
        system_shm.destroy_shared_memory_region(region)


def test_arena_read_serves_memoryview_single_cover():
    import json

    from client_tpu.server.tpu_arena import TpuArena

    arena = TpuArena()
    handle = arena.create_region(1 << 16)
    region_id = json.loads(handle)["region_id"]
    data = np.arange(2048, dtype=np.float32)
    arena.write(region_id, 0, data.tobytes(), "FP32", [2048])
    # Whole-segment and interior windows: zero-assembly memoryview.
    whole = arena.read(region_id, 0, data.nbytes)
    assert isinstance(whole, memoryview)
    assert bytes(whole) == data.tobytes()
    interior = arena.read(region_id, 16, 256)
    assert isinstance(interior, memoryview)
    assert bytes(interior) == data.tobytes()[16:272]
    # Multi-segment window still assembles to bytes (zero-filled gap).
    arena.write(region_id, data.nbytes + 64, b"\x07\x08")
    spanning = arena.read(region_id, 0, data.nbytes + 66)
    assert isinstance(spanning, bytes)
    assert spanning[:data.nbytes] == data.tobytes()
    assert spanning[-2:] == b"\x07\x08"
    assert spanning[data.nbytes:data.nbytes + 64] == b"\x00" * 64


def test_arena_store_then_read_single_copy_bf16():
    import json

    import jax.numpy as jnp

    from client_tpu.server.tpu_arena import TpuArena

    arena = TpuArena()
    handle = arena.create_region(4096)
    region_id = json.loads(handle)["region_id"]
    value = jnp.arange(64, dtype=jnp.bfloat16) / 7
    arena.store(region_id, 0, 4096, value)
    golden = np.asarray(value).tobytes()
    got = arena.read(region_id, 0, len(golden))
    assert bytes(got) == golden


# -- core direct path ------------------------------------------------------


def test_core_direct_path_overlapped_parity():
    """A non-batched model returning fake-device outputs: the core's
    shared fetcher materializes them (overlapped) and the encoded
    response is golden; opting out restores the serial path with the
    same bytes."""
    from client_tpu.protocol import inference_pb2 as pb
    from client_tpu.server.core import InferenceServerCore
    from client_tpu.server.repository import ModelRepository

    class DirectModel(ServedModel):
        max_batch_size = 0

        def __init__(self, name, overlapped):
            super().__init__()
            self.name = name
            self.overlapped_fetch = overlapped
            self.inputs = [TensorSpec("IN", "FP32", [4])]
            self.outputs = [TensorSpec("OUT0", "FP32", [4]),
                            TensorSpec("OUT1", "FP32", [4])]

        def infer(self, inputs, parameters=None):
            array = np.asarray(inputs["IN"], dtype=np.float32)
            return {"OUT0": FakeDeviceArray(array * 2.0, delay_s=0.01),
                    "OUT1": FakeDeviceArray(array * 3.0, delay_s=0.01)}

    repository = ModelRepository()
    repository.add_factory("direct_on",
                           lambda: DirectModel("direct_on", True))
    repository.add_factory("direct_off",
                           lambda: DirectModel("direct_off", False))
    repository.load("direct_on")
    repository.load("direct_off")
    core = InferenceServerCore(repository)
    try:
        responses = {}
        for name in ("direct_on", "direct_off"):
            request = pb.ModelInferRequest(model_name=name, id="r1")
            tensor = request.inputs.add()
            tensor.name = "IN"
            tensor.datatype = "FP32"
            tensor.shape.extend([4])
            request.raw_input_contents.append(
                np.arange(4, dtype=np.float32).tobytes())
            responses[name] = core.infer(request)
        on, off = responses["direct_on"], responses["direct_off"]
        assert [t.name for t in on.outputs] == \
            [t.name for t in off.outputs]
        assert list(on.raw_output_contents) == \
            list(off.raw_output_contents)
        golden = (np.arange(4, dtype=np.float32) * 2.0).tobytes()
        assert on.raw_output_contents[0] == golden
    finally:
        core.shutdown()


def test_core_direct_path_fetches_only_requested_outputs():
    """A subset request must not pay device->host traffic for outputs
    it never asked for: the unrequested output's materialization is
    rigged to raise — fetching it would fail the request."""
    from client_tpu.protocol import inference_pb2 as pb
    from client_tpu.server.core import InferenceServerCore
    from client_tpu.server.repository import ModelRepository

    class SubsetModel(ServedModel):
        name = "subset"
        max_batch_size = 0

        def __init__(self):
            super().__init__()
            self.inputs = [TensorSpec("IN", "FP32", [4])]
            self.outputs = [TensorSpec("WANTED", "FP32", [4]),
                            TensorSpec("UNTOUCHED", "FP32", [4])]

        def infer(self, inputs, parameters=None):
            array = np.asarray(inputs["IN"], dtype=np.float32)
            return {
                "WANTED": FakeDeviceArray(array + 1.0),
                "UNTOUCHED": FakeDeviceArray(
                    array, error=RuntimeError(
                        "unrequested output was fetched")),
            }

    repository = ModelRepository()
    repository.add_factory("subset", SubsetModel)
    repository.load("subset")
    core = InferenceServerCore(repository)
    try:
        request = pb.ModelInferRequest(model_name="subset", id="r1")
        tensor = request.inputs.add()
        tensor.name = "IN"
        tensor.datatype = "FP32"
        tensor.shape.extend([4])
        request.raw_input_contents.append(
            np.arange(4, dtype=np.float32).tobytes())
        request.outputs.add(name="WANTED")
        response = core.infer(request)
        assert [t.name for t in response.outputs] == ["WANTED"]
        assert response.raw_output_contents[0] == \
            (np.arange(4, dtype=np.float32) + 1.0).tobytes()
    finally:
        core.shutdown()
