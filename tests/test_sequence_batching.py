"""Sequence-batching scheduler tests: slot lifecycle, per-sequence
ordering, Direct vs Oldest cross-sequence step fusion, implicit
device-resident state, idle reclamation, queue-policy backlog, and
e2e parity across all four client front-ends (HTTP/gRPC x sync/aio)
plus the decoupled stream path."""

import asyncio
import threading
import time

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
import client_tpu.grpc.aio as grpcclient_aio
import client_tpu.http as httpclient
import client_tpu.http.aio as httpclient_aio
from client_tpu._infer_common import InferInput
from client_tpu.grpc._utils import InferResult, get_inference_request
from client_tpu.models.simple_extra import DynaSequence, SequenceAccumulator
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.http_server import start_http_server_thread
from client_tpu.server.sequence import (
    DEFAULT_CANDIDATE_SEQUENCES,
    SequenceScheduler,
    wants_sequence_batching,
)
from client_tpu.utils import InferenceServerException

GOLDEN_INPUTS = [1, 2, 3, 4, 5]
GOLDEN_OUTPUTS = [1, 3, 6, 10, 15]  # running sum — the single-sequence
# golden both simple_sequence (model-managed state) and dyna_sequence
# (scheduler-managed implicit state) must reproduce byte-identically.


# -- helpers ---------------------------------------------------------------


def _request(model, value, sid, start=False, end=False, batched=False):
    shape = [1, 1] if batched else [1]
    tensor = InferInput("INPUT", shape, "INT32")
    tensor.set_data_from_numpy(
        np.array([value], dtype=np.int32).reshape(shape))
    return get_inference_request(
        model_name=model, inputs=[tensor], outputs=None,
        sequence_id=sid, sequence_start=start, sequence_end=end)


def _core_step(core, model, value, sid, start=False, end=False,
               batched=False):
    response = core.infer(
        _request(model, value, sid, start, end, batched))
    return int(InferResult(response).as_numpy("OUTPUT").reshape(-1)[0])


def _run_sequence(core, model, sid, values=GOLDEN_INPUTS, batched=False):
    return [
        _core_step(core, model, value, sid, start=(i == 0),
                   end=(i == len(values) - 1), batched=batched)
        for i, value in enumerate(values)
    ]


# -- scheduler unit behavior (in-process core) -----------------------------


@pytest.fixture(scope="module")
def core():
    core = build_core(["simple_sequence", "dyna_sequence"], warmup=False)
    yield core
    core.shutdown()


def test_wants_sequence_batching():
    assert wants_sequence_batching(SequenceAccumulator())
    assert wants_sequence_batching(DynaSequence())

    class Plain:
        sequence_batching = False

    assert not wants_sequence_batching(Plain())


def test_direct_golden(core):
    assert _run_sequence(core, "simple_sequence", 1001) == GOLDEN_OUTPUTS


def test_oldest_implicit_state_golden(core):
    """dyna_sequence's state lives in the scheduler (device arrays),
    not the model — results must match the simple_sequence golden."""
    assert _run_sequence(core, "dyna_sequence", 1002,
                         batched=True) == GOLDEN_OUTPUTS


def test_state_output_not_in_response(core):
    response = core.infer(
        _request("dyna_sequence", 5, 1003, start=True, end=True,
                 batched=True))
    names = [t.name for t in response.outputs]
    assert "OUTPUT" in names
    assert "STATE_OUT" not in names  # implicit state stays server-side


def test_sequence_not_started(core):
    with pytest.raises(InferenceServerException) as exc:
        _core_step(core, "simple_sequence", 1, 55555)
    assert "not started" in str(exc.value)
    assert exc.value.status() == "INVALID_ARGUMENT"


def test_step_after_end_fails(core):
    _run_sequence(core, "simple_sequence", 1004)
    with pytest.raises(InferenceServerException) as exc:
        _core_step(core, "simple_sequence", 1, 1004)
    assert "not started" in str(exc.value)


def test_restart_resets_state(core):
    _run_sequence(core, "dyna_sequence", 1005, batched=True)
    # same corrid, fresh start: accumulation restarts from zero
    assert _run_sequence(core, "dyna_sequence", 1005,
                         batched=True) == GOLDEN_OUTPUTS


def test_oldest_fusion_across_sequences(core):
    """>= 8 live sequences, Oldest strategy: steps from distinct
    sequences fuse into shared executions — execution_count strictly
    below request_count (the acceptance-criteria shape)."""
    stats0 = core.model_statistics("dyna_sequence").model_stats[0]
    results = {}
    values = list(range(1, 11))

    def run_one(sid):
        results[sid] = _run_sequence(core, "dyna_sequence", sid,
                                     values=values, batched=True)

    threads = [threading.Thread(target=run_one, args=(2000 + i,))
               for i in range(10)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    golden = list(np.cumsum(values))
    for sid, outputs in results.items():
        assert outputs == golden, "sequence %d broke: %s" % (sid, outputs)
    stats1 = core.model_statistics("dyna_sequence").model_stats[0]
    d_requests = stats1.inference_count - stats0.inference_count
    d_executions = stats1.execution_count - stats0.execution_count
    assert d_requests == 100
    assert d_executions < d_requests, (
        "no cross-sequence fusion: %d executions for %d requests"
        % (d_executions, d_requests))
    seq = stats1.sequence_stats
    assert seq.slot_total == 16
    assert seq.fused_steps >= 100
    assert seq.sequences_completed >= 10


def test_direct_sequences_never_fuse(core):
    """Direct strategy executes steps singly even under concurrency
    (the model's own params-keyed state requires it)."""
    stats0 = core.model_statistics("simple_sequence").model_stats[0]
    results = {}

    def run_one(sid):
        results[sid] = _run_sequence(core, "simple_sequence", sid)

    threads = [threading.Thread(target=run_one, args=(3000 + i,))
               for i in range(8)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    for outputs in results.values():
        assert outputs == GOLDEN_OUTPUTS
    stats1 = core.model_statistics("simple_sequence").model_stats[0]
    assert (stats1.execution_count - stats0.execution_count
            == stats1.inference_count - stats0.inference_count)


def test_per_sequence_ordering_under_concurrency():
    """Steps admitted in order execute in order even when later steps
    are dispatched from concurrent threads while earlier ones run."""

    class SlowModel(SequenceAccumulator):
        def infer(self, inputs, parameters=None):
            time.sleep(0.02)
            return super().infer(inputs, parameters)

    model = SlowModel(name="slow_sequence")
    scheduler = SequenceScheduler(model)
    outputs = []
    lock = threading.Lock()
    threads = []

    def run_step(value, start, end):
        out, _, _ = scheduler.infer(
            {"INPUT": np.array([value], dtype=np.int32)},
            {"sequence_id": 42, "sequence_start": start,
             "sequence_end": end}, 1)
        with lock:
            outputs.append(int(np.asarray(out["OUTPUT"]).reshape(-1)[0]))

    # Admit each step under the scheduler's turnstile IN ORDER (tickets
    # issue at admission), then let the executions race.
    for i, value in enumerate(GOLDEN_INPUTS):
        thread = threading.Thread(
            target=run_step,
            args=(value, i == 0, i == len(GOLDEN_INPUTS) - 1))
        thread.start()
        threads.append(thread)
        time.sleep(0.005)  # admission order = arrival order
    for thread in threads:
        thread.join()
    assert outputs == GOLDEN_OUTPUTS
    scheduler.stop()


def test_idle_timeout_reclaims_slot():
    model = SequenceAccumulator(name="idle_sequence",
                                max_sequence_idle_us=50_000,
                                max_candidate_sequences=2)
    scheduler = SequenceScheduler(model)

    def step(value, sid, start=False, end=False):
        out, _, _ = scheduler.infer(
            {"INPUT": np.array([value], dtype=np.int32)},
            {"sequence_id": sid, "sequence_start": start,
             "sequence_end": end}, 1)
        return int(np.asarray(out["OUTPUT"]).reshape(-1)[0])

    assert step(1, 7, start=True) == 1
    time.sleep(0.3)  # > max_sequence_idle_us: the reaper frees slot 7
    with pytest.raises(InferenceServerException) as exc:
        step(2, 7)
    assert "not started" in str(exc.value)
    snap = scheduler.stats_snapshot()
    assert snap["idle_reclaimed_total"] == 1
    assert snap["active_sequences"] == 0
    # the reclaimed slot is reusable by new sequences
    assert step(5, 8, start=True, end=True) == 5
    scheduler.stop()


def test_backlog_rejects_when_bounded():
    """All slots busy + bounded backlog: a new start is rejected
    UNAVAILABLE at admission (PR-2 queue-policy semantics)."""
    model = SequenceAccumulator(name="tiny_sequence",
                                max_candidate_sequences=1)
    model.max_queue_size = 1  # backlog admits at most one waiter
    rejects = []
    scheduler = SequenceScheduler(model, reject_hook=lambda:
                                  rejects.append(1))

    def start_seq(sid):
        scheduler.infer(
            {"INPUT": np.array([1], dtype=np.int32)},
            {"sequence_id": sid, "sequence_start": True}, 1)

    start_seq(1)  # occupies the only slot (never ended)
    blocked_outcome = []

    def blocked_start():
        try:
            start_seq(2)
        except InferenceServerException as e:
            blocked_outcome.append(e.status())

    blocked = threading.Thread(target=blocked_start, daemon=True)
    blocked.start()  # fills the backlog (waits forever; no deadline)
    time.sleep(0.1)
    with pytest.raises(InferenceServerException) as exc:
        start_seq(3)
    assert exc.value.status() == "UNAVAILABLE"
    assert rejects == [1]
    scheduler.stop()  # wakes the backlogged start with UNAVAILABLE
    blocked.join(timeout=5)
    assert not blocked.is_alive()
    assert blocked_outcome == ["UNAVAILABLE"]


def test_backlog_start_times_out():
    model = SequenceAccumulator(name="deadline_sequence",
                                max_candidate_sequences=1)
    model.default_queue_policy_timeout_us = 50_000
    timeouts = []
    scheduler = SequenceScheduler(model, timeout_hook=lambda:
                                  timeouts.append(1))
    scheduler.infer(
        {"INPUT": np.array([1], dtype=np.int32)},
        {"sequence_id": 1, "sequence_start": True}, 1)
    t0 = time.monotonic()
    with pytest.raises(InferenceServerException) as exc:
        scheduler.infer(
            {"INPUT": np.array([1], dtype=np.int32)},
            {"sequence_id": 2, "sequence_start": True}, 1)
    assert exc.value.status() == "DEADLINE_EXCEEDED"
    assert time.monotonic() - t0 < 5.0
    assert timeouts == [1]
    scheduler.stop()


def test_duplicate_concurrent_starts_share_one_slot():
    """Two racing starts for the same corrid that both backlog must
    resolve to ONE slot (the loser joins the winner's) — a duplicate
    allocation would leak a slot index forever."""
    model = SequenceAccumulator(name="dup_sequence",
                                max_candidate_sequences=2)
    scheduler = SequenceScheduler(model)

    def step(sid, value, start=False, end=False):
        out, _, _ = scheduler.infer(
            {"INPUT": np.array([value], dtype=np.int32)},
            {"sequence_id": sid, "sequence_start": start,
             "sequence_end": end}, 1)
        return int(np.asarray(out["OUTPUT"]).reshape(-1)[0])

    step(1, 1, start=True)
    step(2, 1, start=True)  # both slots busy
    results = []
    threads = [
        threading.Thread(target=lambda: results.append(
            step(7, 5, start=True)))
        for _ in range(2)
    ]
    for thread in threads:
        thread.start()
    time.sleep(0.2)  # both duplicate starts now wait in the backlog
    step(1, 1, end=True)
    step(2, 1, end=True)  # frees both slots; both waiters wake
    for thread in threads:
        thread.join(timeout=10)
    assert len(results) == 2
    snap = scheduler.stats_snapshot()
    assert snap["active_sequences"] == 1  # corrid 7 holds ONE slot
    assert len(scheduler._free_slots) == 1
    step(7, 1, end=True)
    assert len(scheduler._free_slots) == 2  # no leaked slot index
    scheduler.stop()


def test_negative_corrid_with_unsigned_control():
    """A correlation id outside the CORRID control dtype's range (here
    -1 vs UINT64) takes the hash fallback instead of failing the
    step."""
    model = DynaSequence(name="neg_corrid_sequence")
    scheduler = SequenceScheduler(model)
    out, _, _ = scheduler.infer(
        {"INPUT": np.array([[4]], dtype=np.int32)},
        {"sequence_id": -1, "sequence_start": True,
         "sequence_end": True}, 1)
    assert int(np.asarray(out["OUTPUT"]).reshape(-1)[0]) == 4
    scheduler.stop()


def test_implicit_state_stays_device_resident():
    """The state handed between steps must be a device array (jax) —
    never silently materialized to host by the scheduler."""
    import jax

    model = DynaSequence(name="resident_sequence")
    scheduler = SequenceScheduler(model)
    scheduler.infer(
        {"INPUT": np.array([[3]], dtype=np.int32)},
        {"sequence_id": 5, "sequence_start": True}, 1)
    slot = scheduler._sequences[5]
    state = slot.state["STATE_IN"]
    assert isinstance(state, jax.Array)
    assert int(np.asarray(state).reshape(-1)[0]) == 3
    scheduler.stop()


# -- config rendering over both transports ---------------------------------


@pytest.fixture(scope="module")
def servers(core):
    grpc_handle = start_grpc_server(core=core)
    http_runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield grpc_handle, http_runner
    http_runner.stop()
    # grpc_handle.stop() also calls core.shutdown(); the core fixture's
    # own shutdown after this is a no-op second call.
    grpc_handle.stop()


def _check_config_dict(config):
    sb = config["sequence_batching"]
    assert sb["strategy"] == "oldest"
    assert int(sb["max_candidate_sequences"]) == 16
    assert int(sb["max_sequence_idle_microseconds"]) == 5_000_000
    kinds = {c["kind"]: c["name"] for c in sb["control_input"]}
    assert kinds == {
        "CONTROL_SEQUENCE_CORRID": "CORRID",
        "CONTROL_SEQUENCE_START": "START",
        "CONTROL_SEQUENCE_END": "END",
        "CONTROL_SEQUENCE_READY": "READY",
    }
    (state,) = sb["state"]
    assert state["input_name"] == "STATE_IN"
    assert state["output_name"] == "STATE_OUT"
    assert [int(d) for d in state["dims"]] == [1]
    assert [int(s) for s in sb["preferred_batch_size"]] == [4, 8]


def test_grpc_config_renders_sequence_batching(servers):
    grpc_handle, _ = servers
    with grpcclient.InferenceServerClient(grpc_handle.address) as client:
        config = client.get_model_config("dyna_sequence", as_json=True)
        _check_config_dict(config.get("config", config))
        simple = client.get_model_config("simple_sequence", as_json=True)
        simple = simple.get("config", simple)
        assert simple["sequence_batching"]["strategy"] == "direct"


def test_http_config_renders_sequence_batching(servers):
    _, http_runner = servers
    with httpclient.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port) as client:
        _check_config_dict(client.get_model_config("dyna_sequence"))


def test_model_parser_full_sequence_config(servers):
    from client_tpu.perf.client_backend import (
        BackendKind,
        ClientBackendFactory,
    )
    from client_tpu.perf.model_parser import ModelParser, SchedulerType

    _, http_runner = servers
    factory = ClientBackendFactory(BackendKind.TRITON_HTTP,
                                   url="127.0.0.1:%d" % http_runner.port)
    backend = factory.create()
    try:
        parsed = ModelParser().parse(backend, "dyna_sequence")
    finally:
        backend.close()
    assert parsed.scheduler_type is SchedulerType.SEQUENCE
    assert parsed.sequence_strategy == "oldest"
    assert parsed.max_candidate_sequences == 16
    assert parsed.max_sequence_idle_us == 5_000_000
    assert {c["kind"] for c in parsed.sequence_controls} == {
        "CONTROL_SEQUENCE_CORRID", "CONTROL_SEQUENCE_START",
        "CONTROL_SEQUENCE_END", "CONTROL_SEQUENCE_READY"}
    assert parsed.sequence_states[0]["input_name"] == "STATE_IN"
    assert parsed.sequence_preferred_batch_sizes == [4, 8]


# -- e2e over the four front-ends ------------------------------------------


def _client_sequence(client, model, sid, batched, infer):
    outputs = []
    for i, value in enumerate(GOLDEN_INPUTS):
        shape = [1, 1] if batched else [1]
        tensor = InferInput("INPUT", shape, "INT32")
        tensor.set_data_from_numpy(
            np.array([value], dtype=np.int32).reshape(shape))
        result = infer(client, model, [tensor], sid,
                       i == 0, i == len(GOLDEN_INPUTS) - 1)
        outputs.append(int(result.as_numpy("OUTPUT").reshape(-1)[0]))
    return outputs


@pytest.mark.parametrize("model,batched", [
    ("simple_sequence", False),
    ("dyna_sequence", True),
])
def test_grpc_sync_sequence_e2e(servers, model, batched):
    grpc_handle, _ = servers

    def infer(client, model_name, inputs, sid, start, end):
        return client.infer(model_name, inputs, sequence_id=sid,
                            sequence_start=start, sequence_end=end)

    with grpcclient.InferenceServerClient(grpc_handle.address) as client:
        assert _client_sequence(client, model, 4100 + batched, batched,
                                infer) == GOLDEN_OUTPUTS


@pytest.mark.parametrize("model,batched", [
    ("simple_sequence", False),
    ("dyna_sequence", True),
])
def test_http_sync_sequence_e2e(servers, model, batched):
    _, http_runner = servers

    def infer(client, model_name, inputs, sid, start, end):
        return client.infer(model_name, inputs, sequence_id=sid,
                            sequence_start=start, sequence_end=end)

    with httpclient.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port) as client:
        assert _client_sequence(client, model, 4200 + batched, batched,
                                infer) == GOLDEN_OUTPUTS


@pytest.mark.parametrize("model,batched", [
    ("simple_sequence", False),
    ("dyna_sequence", True),
])
def test_grpc_aio_sequence_e2e(servers, model, batched):
    grpc_handle, _ = servers

    async def run():
        async with grpcclient_aio.InferenceServerClient(
                grpc_handle.address) as client:
            outputs = []
            for i, value in enumerate(GOLDEN_INPUTS):
                shape = [1, 1] if batched else [1]
                tensor = InferInput("INPUT", shape, "INT32")
                tensor.set_data_from_numpy(
                    np.array([value], dtype=np.int32).reshape(shape))
                result = await client.infer(
                    model, [tensor], sequence_id=4300 + batched,
                    sequence_start=(i == 0),
                    sequence_end=(i == len(GOLDEN_INPUTS) - 1))
                outputs.append(
                    int(result.as_numpy("OUTPUT").reshape(-1)[0]))
            assert outputs == GOLDEN_OUTPUTS

    asyncio.run(run())


@pytest.mark.parametrize("model,batched", [
    ("simple_sequence", False),
    ("dyna_sequence", True),
])
def test_http_aio_sequence_e2e(servers, model, batched):
    _, http_runner = servers

    async def run():
        async with httpclient_aio.InferenceServerClient(
                "127.0.0.1:%d" % http_runner.port) as client:
            outputs = []
            for i, value in enumerate(GOLDEN_INPUTS):
                shape = [1, 1] if batched else [1]
                tensor = InferInput("INPUT", shape, "INT32")
                tensor.set_data_from_numpy(
                    np.array([value], dtype=np.int32).reshape(shape))
                result = await client.infer(
                    model, [tensor], sequence_id=4400 + batched,
                    sequence_start=(i == 0),
                    sequence_end=(i == len(GOLDEN_INPUTS) - 1))
                outputs.append(
                    int(result.as_numpy("OUTPUT").reshape(-1)[0]))
            assert outputs == GOLDEN_OUTPUTS

    asyncio.run(run())


# -- streaming-path parity -------------------------------------------------


@pytest.mark.parametrize("model,batched", [
    ("simple_sequence", False),
    ("dyna_sequence", True),
])
def test_stream_sequence_parity(servers, model, batched):
    """The bidi-stream path routes through the same scheduler: ordered
    per-sequence results, interleaved across two live sequences."""
    grpc_handle, _ = servers
    got = {}
    lock = threading.Lock()
    expected_total = 2 * len(GOLDEN_INPUTS)
    done = threading.Event()
    errors = []

    def callback(result, error):
        if error is not None:
            errors.append(error)
            done.set()
            return
        rid = result.get_response().id
        sid = int(rid.split("-")[0])
        with lock:
            got.setdefault(sid, []).append(
                int(result.as_numpy("OUTPUT").reshape(-1)[0]))
            if sum(len(v) for v in got.values()) == expected_total:
                done.set()

    with grpcclient.InferenceServerClient(grpc_handle.address) as client:
        client.start_stream(callback)
        sids = (4500 + batched * 10, 4501 + batched * 10)
        for i, value in enumerate(GOLDEN_INPUTS):
            for sid in sids:  # interleave the two sequences' steps
                shape = [1, 1] if batched else [1]
                tensor = InferInput("INPUT", shape, "INT32")
                tensor.set_data_from_numpy(
                    np.array([value], dtype=np.int32).reshape(shape))
                client.async_stream_infer(
                    model, [tensor], request_id="%d-%d" % (sid, i),
                    sequence_id=sid, sequence_start=(i == 0),
                    sequence_end=(i == len(GOLDEN_INPUTS) - 1))
        assert done.wait(timeout=60), "stream timed out: got %s" % got
        client.stop_stream()
    assert not errors, errors
    for sid in sids:
        assert got[sid] == GOLDEN_OUTPUTS


def test_default_candidate_slots_rendered(core):
    config = core.model_config("simple_sequence").config
    assert config.sequence_batching.max_candidate_sequences == \
        DEFAULT_CANDIDATE_SEQUENCES
    assert config.sequence_batching.strategy == "direct"
