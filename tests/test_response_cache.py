"""Response-cache tests: content-addressed keying, byte-budgeted LRU
eviction, hit/miss golden parity e2e over all four client front-ends
(HTTP/gRPC x sync/aio), single-flight coalescing under concurrency,
sequence/decoupled bypass, invalidation on model reload, and the
statistics / Prometheus observability surface."""

import asyncio
import threading
import time

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
import client_tpu.grpc.aio as grpcclient_aio
import client_tpu.http as httpclient
import client_tpu.http.aio as httpclient_aio
from client_tpu._infer_common import InferInput
from client_tpu.grpc._utils import InferResult, get_inference_request
from client_tpu.models.add_sub import AddSub
from client_tpu.models.simple_extra import SequenceAccumulator
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.cache import (
    ResponseCache,
    request_cache_key,
    wants_response_cache,
)
from client_tpu.server.http_server import start_http_server_thread
from client_tpu.utils import InferenceServerException


# -- helpers ---------------------------------------------------------------


def _request(value, model="simple_cache", shape=(1, 16), timeout=None,
             **kwargs):
    """Two-input add/sub request whose content is fully determined by
    ``value`` (INPUT0 = value, INPUT1 = 2*value)."""
    tensors = []
    for name, fill in (("INPUT0", value), ("INPUT1", 2 * value)):
        tensor = InferInput(name, list(shape), "INT32")
        tensor.set_data_from_numpy(
            np.full(shape, fill, dtype=np.int32))
        tensors.append(tensor)
    return get_inference_request(
        model_name=model, inputs=tensors, outputs=None, timeout=timeout,
        **kwargs)


def _infer_value(core, value, model="simple_cache", **kwargs):
    response = core.infer(_request(value, model=model, **kwargs))
    return int(InferResult(response).as_numpy("OUTPUT0").reshape(-1)[0])


def _cache_counters(core, model="simple_cache"):
    entry = core.model_statistics(model).model_stats[0]
    return {
        "inference": int(entry.inference_count),
        "execution": int(entry.execution_count),
        "hit": int(entry.cache_hit_count),
        "miss": int(entry.cache_miss_count),
        "timeout": int(entry.timeout_count),
    }


class CountingModel(AddSub):
    """Cache-enabled add/sub (no batcher) that counts real executions
    and can be slowed down or made to fail."""

    response_cache = True

    def __init__(self, name, delay_s=0.0, fail_first=False):
        super().__init__(name=name, datatype="INT32", shape=(16,))
        self.calls = 0
        self._calls_lock = threading.Lock()
        self._delay_s = delay_s
        self._fail_first = fail_first

    def infer(self, inputs, parameters=None):
        with self._calls_lock:
            self.calls += 1
            fail = self._fail_first and self.calls == 1
        if self._delay_s:
            time.sleep(self._delay_s)
        if fail:
            raise InferenceServerException(
                "injected leader failure", status="INTERNAL")
        return super().infer(inputs, parameters)


# -- keying rules (unit) ---------------------------------------------------


def test_cache_key_content_addressing():
    a = request_cache_key("m", "1", _request(3))
    b = request_cache_key("m", "1", _request(3))
    assert a == b
    assert request_cache_key("m", "1", _request(4)) != a
    assert request_cache_key("other", "1", _request(3)) != a
    assert request_cache_key("m", "2", _request(3)) != a
    # request id and QoS params are NOT part of the content address
    tagged = _request(3)
    tagged.id = "req-77"
    assert request_cache_key("m", "1", tagged) == a
    assert request_cache_key("m", "1", _request(3, timeout=5000)) == a
    # a response-shaping param IS part of it
    named = _request(3, parameters={"custom": 1})
    assert request_cache_key("m", "1", named) != a


def test_cache_key_bypasses():
    # correlated (stateful) requests never cache
    assert request_cache_key(
        "m", "1", _request(3, sequence_id=7, sequence_start=True)) is None
    # shared-memory input regions are not content-addressable
    shm = _request(3)
    shm.inputs[0].parameters["shared_memory_region"].string_param = "r0"
    assert request_cache_key("m", "1", shm) is None
    # shm outputs need per-request side effects
    out = _request(3)
    tensor = out.outputs.add()
    tensor.name = "OUTPUT0"
    tensor.parameters["shared_memory_region"].string_param = "r1"
    assert request_cache_key("m", "1", out) is None


def test_wants_response_cache_rules():
    model = AddSub(name="x")
    assert not wants_response_cache(model)
    model.response_cache = True
    assert wants_response_cache(model)
    model.decoupled = True  # decoupled models never cache
    assert not wants_response_cache(model)


# -- LRU / byte budget (unit) ---------------------------------------------


def _response(size, marker=0):
    response = pb.ModelInferResponse(model_name="m")
    response.raw_output_contents.append(bytes([marker % 256]) * size)
    return response


def test_lru_eviction_under_byte_budget():
    cache = ResponseCache(max_bytes=1500)
    keys = [("k%d" % i).encode() for i in range(5)]
    for i, key in enumerate(keys):
        assert cache.insert("m", key, _response(300, i))
    # ~310 payload + 128 overhead bytes/entry: only the 3 most recent
    # survive the 1500-byte budget
    assert cache.lookup(keys[0]) is None
    assert cache.lookup(keys[1]) is None
    assert cache.total_bytes() <= 1500
    snap = cache.snapshot()["m"]
    assert snap["entries"] == cache.total_entries() == 3
    assert snap["evictions"] == 2
    # a lookup refreshes recency: keys[2] survives the next insert
    assert cache.lookup(keys[2]) is not None
    cache.insert("m", b"fresh", _response(300))
    assert cache.lookup(keys[2]) is not None
    assert cache.lookup(keys[3]) is None  # the new LRU victim


def test_oversized_response_never_cached():
    cache = ResponseCache(max_bytes=100)
    assert not cache.insert("m", b"big", _response(500))
    assert cache.total_entries() == 0
    assert cache.snapshot()["m"]["insert_skipped"] == 1


def test_insert_serializes_and_clears_id():
    cache = ResponseCache(max_bytes=1000)
    response = _response(10)
    response.id = "caller-id"
    cache.insert("m", b"k", response)
    response.raw_output_contents[0] = b"mutated!"
    stored = pb.ModelInferResponse()
    stored.ParseFromString(cache.lookup(b"k"))
    assert stored.id == ""  # hits are re-stamped per requester
    assert stored.raw_output_contents[0] != b"mutated!"


def test_lookup_or_begin_is_atomic_after_resolution():
    """A thread whose plain lookup missed must NOT become a second
    leader once the first leader has inserted+resolved — the atomic
    probe returns the entry instead."""
    cache = ResponseCache(max_bytes=10_000)
    _, flight, leader = cache.lookup_or_begin(b"k")
    assert leader
    cache.insert("m", b"k", _response(10))
    cache.resolve_flight(b"k", flight, _response(10))
    cached, late_flight, late_leader = cache.lookup_or_begin(b"k")
    assert cached is not None
    assert late_flight is None and not late_leader


def test_invalidate_model_drops_only_its_entries():
    cache = ResponseCache(max_bytes=10_000)
    cache.insert("a", b"ka", _response(50))
    cache.insert("b", b"kb", _response(50))
    assert cache.invalidate_model("a") == 1
    assert cache.lookup(b"ka") is None
    assert cache.lookup(b"kb") is not None
    assert cache.snapshot()["a"]["entries"] == 0


# -- core hit/miss behavior ------------------------------------------------


@pytest.fixture(scope="module")
def core():
    core = build_core(["simple_cache"], warmup=False)
    yield core
    core.shutdown()


def test_hit_miss_golden_parity(core):
    before = _cache_counters(core)
    first = core.infer(_request(21))
    second = core.infer(_request(21))
    for name in ("OUTPUT0", "OUTPUT1"):
        np.testing.assert_array_equal(
            InferResult(first).as_numpy(name),
            InferResult(second).as_numpy(name))
    assert int(InferResult(second).as_numpy("OUTPUT0")[0, 0]) == 63
    after = _cache_counters(core)
    # Triton semantics: the hit counts toward inference_count but the
    # model executed once.
    assert after["inference"] - before["inference"] == 2
    assert after["execution"] - before["execution"] == 1
    assert after["hit"] - before["hit"] == 1
    assert after["miss"] - before["miss"] == 1


def test_hit_carries_requester_id(core):
    core.infer(_request(22))
    request = _request(22)
    request.id = "my-request"
    response = core.infer(request)
    assert response.id == "my-request"


def test_distinct_content_always_misses(core):
    before = _cache_counters(core)
    for value in range(300, 305):
        _infer_value(core, value)
    after = _cache_counters(core)
    assert after["miss"] - before["miss"] == 5
    assert after["hit"] == before["hit"]


def test_hit_duration_stats_rendered(core):
    core.infer(_request(23))
    core.infer(_request(23))
    entry = core.model_statistics("simple_cache").model_stats[0]
    stats = entry.inference_stats
    assert stats.cache_hit.count == entry.cache_hit_count > 0
    assert stats.cache_hit.ns > 0
    assert stats.cache_miss.count == entry.cache_miss_count > 0
    assert stats.cache_miss.ns > stats.cache_hit.ns / max(
        stats.cache_hit.count, 1)  # misses executed, hits did not


# -- single-flight deduplication -------------------------------------------


def test_single_flight_coalesces_concurrent_misses():
    core = build_core([], warmup=False)
    model = CountingModel("sf_model", delay_s=0.15)
    core.repository.add_model(model)
    barrier = threading.Barrier(6)
    results = []
    lock = threading.Lock()

    def worker():
        barrier.wait()
        value = _infer_value(core, 9, model="sf_model", shape=(16,))
        with lock:
            results.append(value)

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    assert results == [27] * 6
    assert model.calls == 1  # one leader executed; followers coalesced
    counters = _cache_counters(core, "sf_model")
    assert counters["miss"] == 1
    assert counters["hit"] == 5
    assert counters["execution"] == 1
    assert core.response_cache.snapshot()["sf_model"]["coalesced"] == 5
    core.shutdown()


def test_follower_deadline_bounds_the_wait():
    core = build_core([], warmup=False)
    model = CountingModel("slow_model", delay_s=0.6)
    core.repository.add_model(model)
    leader_done = []

    def leader():
        leader_done.append(
            _infer_value(core, 4, model="slow_model", shape=(16,)))

    leader_thread = threading.Thread(target=leader)
    leader_thread.start()
    time.sleep(0.1)  # the leader is now executing
    t0 = time.monotonic()
    with pytest.raises(InferenceServerException) as exc:
        _infer_value(core, 4, model="slow_model", shape=(16,),
                     timeout=100_000)  # 100 ms deadline, 600 ms leader
    assert exc.value.status() == "DEADLINE_EXCEEDED"
    assert time.monotonic() - t0 < 0.5  # expired before the leader
    leader_thread.join()
    assert leader_done == [12]
    assert _cache_counters(core, "slow_model")["timeout"] == 1
    core.shutdown()


def test_follower_delay_action_keeps_deadline_advisory():
    """timeout_action=DELAY (PR-2): the queue deadline never hard-fails
    a request — a coalesced follower must wait the leader out instead
    of raising DEADLINE_EXCEEDED."""
    core = build_core([], warmup=False)
    model = CountingModel("delay_model", delay_s=0.3)
    model.default_queue_policy_timeout_us = 50_000  # << leader's 300ms
    model.timeout_action = "DELAY"
    core.repository.add_model(model)
    barrier = threading.Barrier(2)
    results = []
    lock = threading.Lock()

    def worker():
        barrier.wait()
        value = _infer_value(core, 3, model="delay_model", shape=(16,))
        with lock:
            results.append(value)

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    assert results == [9, 9]
    assert model.calls == 1  # the follower coalesced, never expired
    assert _cache_counters(core, "delay_model")["timeout"] == 0
    core.shutdown()


def test_follower_deadline_accepts_string_timeout():
    """HTTP clients send `timeout` as a string parameter; the follower
    wait must honor it like the batcher does (same coercion)."""
    core = build_core([], warmup=False)
    model = CountingModel("strto_model", delay_s=0.6)
    core.repository.add_model(model)
    leader = threading.Thread(
        target=lambda: _infer_value(core, 4, model="strto_model",
                                    shape=(16,)))
    leader.start()
    time.sleep(0.1)
    follower_request = _request(4, model="strto_model", shape=(16,))
    follower_request.parameters["timeout"].string_param = "100000"
    t0 = time.monotonic()
    with pytest.raises(InferenceServerException) as exc:
        core.infer(follower_request)
    assert exc.value.status() == "DEADLINE_EXCEEDED"
    assert time.monotonic() - t0 < 0.5
    leader.join()
    core.shutdown()


def test_leader_failure_falls_back_not_fans_out():
    core = build_core([], warmup=False)
    model = CountingModel("flaky_model", delay_s=0.15, fail_first=True)
    core.repository.add_model(model)
    barrier = threading.Barrier(4)
    outcomes = []
    lock = threading.Lock()

    def worker():
        barrier.wait()
        try:
            value = _infer_value(core, 6, model="flaky_model", shape=(16,))
            with lock:
                outcomes.append(value)
        except InferenceServerException as e:
            with lock:
                outcomes.append(e.status())

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    # Exactly the leader fails; followers fall back to their own
    # executions instead of inheriting the failure.
    assert outcomes.count("INTERNAL") == 1
    assert outcomes.count(18) == 3
    assert model.calls == 4  # 1 failed leader + 3 independent fallbacks
    # the failure was never inserted: the cached entry (from a
    # fallback success) serves the next request
    assert _infer_value(core, 6, model="flaky_model", shape=(16,)) == 18
    assert model.calls == 4
    core.shutdown()


def test_failed_execution_not_inserted():
    core = build_core([], warmup=False)
    model = CountingModel("fail_model", fail_first=True)
    core.repository.add_model(model)
    with pytest.raises(InferenceServerException):
        _infer_value(core, 5, model="fail_model", shape=(16,))
    assert core.response_cache.snapshot().get(
        "fail_model", {}).get("entries", 0) == 0
    # the same request executes again (no poisoned entry) and succeeds
    assert _infer_value(core, 5, model="fail_model", shape=(16,)) == 15
    assert model.calls == 2
    core.shutdown()


# -- bypass rules ----------------------------------------------------------


def test_sequence_requests_bypass_cache():
    core = build_core([], warmup=False)
    model = SequenceAccumulator(name="seq_cache")
    model.response_cache = True  # even opted in, sequences bypass
    core.repository.add_model(model)

    def step(value, start=False, end=False):
        tensor = InferInput("INPUT", [1], "INT32")
        tensor.set_data_from_numpy(np.array([value], dtype=np.int32))
        request = get_inference_request(
            model_name="seq_cache", inputs=[tensor], outputs=None,
            sequence_id=31, sequence_start=start, sequence_end=end)
        return int(InferResult(core.infer(request))
                   .as_numpy("OUTPUT").reshape(-1)[0])

    # identical step payloads MUST produce different (accumulated)
    # results — a cached response would repeat the first
    assert step(2, start=True) == 2
    assert step(2) == 4
    assert step(2, end=True) == 6
    counters = _cache_counters(core, "seq_cache")
    assert counters["hit"] == 0 and counters["miss"] == 0
    core.shutdown()


def test_invalidation_on_unload_reload(core):
    assert _infer_value(core, 41) == 123
    assert _infer_value(core, 41) == 123
    before = _cache_counters(core)
    assert core.response_cache.snapshot()["simple_cache"]["entries"] > 0
    core.unload_model("simple_cache")
    assert core.response_cache.snapshot()["simple_cache"]["entries"] == 0
    core.load_model("simple_cache")
    assert _infer_value(core, 41) == 123
    after = _cache_counters(core)
    assert after["miss"] - before["miss"] == 1  # cold again post-reload


# -- observability ---------------------------------------------------------


def test_prometheus_cache_families(core):
    core.infer(_request(51))
    core.infer(_request(51))
    text = core.metrics_text()
    for family in ("tpu_cache_hit_total", "tpu_cache_miss_total",
                   "tpu_cache_size_bytes", "tpu_cache_entries",
                   "tpu_cache_evictions_total"):
        assert family in text, family
    from client_tpu.perf.metrics_manager import (
        parse_prometheus,
        summarize_metrics,
    )

    snap = parse_prometheus(text)
    assert snap.cache_hit_total["simple_cache"] >= 1
    assert snap.cache_entries["simple_cache"] >= 1
    assert snap.cache_size_bytes["simple_cache"] > 0
    # gauge-aware window deltas: counters difference first->last
    later = parse_prometheus(core.metrics_text())
    later.cache_hit_total["simple_cache"] += 3
    summary = summarize_metrics([snap, later])
    assert summary["cache_hit_total"]["delta"] == 3
    assert summary["cache_entries"]["avg"] >= 1


def test_eviction_end_to_end_under_tight_budget():
    core = build_core([], warmup=False, cache_size=600)
    model = CountingModel("tiny_cache")
    core.repository.add_model(model)
    for value in range(60, 70):
        _infer_value(core, value, model="tiny_cache", shape=(16,))
    snap = core.response_cache.snapshot()["tiny_cache"]
    assert snap["evictions"] > 0
    assert core.response_cache.total_bytes() <= 600
    assert "tpu_cache_evictions_total{model=\"tiny_cache\"} %d" \
        % snap["evictions"] in core.metrics_text()
    core.shutdown()


def test_cache_size_zero_disables():
    core = build_core([], warmup=False, cache_size=0)
    model = CountingModel("nocache_model")
    core.repository.add_model(model)
    assert _infer_value(core, 8, model="nocache_model", shape=(16,)) == 24
    assert _infer_value(core, 8, model="nocache_model", shape=(16,)) == 24
    assert model.calls == 2  # every request executed
    counters = _cache_counters(core, "nocache_model")
    assert counters["hit"] == 0 and counters["miss"] == 0
    core.shutdown()


# -- e2e over all four client front-ends -----------------------------------


@pytest.fixture(scope="module")
def servers(core):
    grpc_handle = start_grpc_server(core=core)
    http_runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield grpc_handle, http_runner
    http_runner.stop()
    grpc_handle.stop()


def _client_inputs(value, cls):
    tensors = []
    for name, fill in (("INPUT0", value), ("INPUT1", 2 * value)):
        tensor = cls(name, [1, 16], "INT32")
        tensor.set_data_from_numpy(np.full((1, 16), fill, dtype=np.int32))
        tensors.append(tensor)
    return tensors


def _assert_parity(first, second, value):
    np.testing.assert_array_equal(first.as_numpy("OUTPUT0"),
                                  second.as_numpy("OUTPUT0"))
    np.testing.assert_array_equal(first.as_numpy("OUTPUT1"),
                                  second.as_numpy("OUTPUT1"))
    assert int(first.as_numpy("OUTPUT0")[0, 0]) == 3 * value
    assert int(first.as_numpy("OUTPUT1")[0, 0]) == -value


def test_grpc_hit_miss_parity(servers):
    grpc_handle, _ = servers
    with grpcclient.InferenceServerClient(grpc_handle.address) as client:
        inputs = _client_inputs(71, grpcclient.InferInput)
        first = client.infer("simple_cache", inputs)
        second = client.infer("simple_cache", inputs)
        _assert_parity(first, second, 71)
        stats = client.get_inference_statistics("simple_cache")
        entry = stats.model_stats[0]
        assert entry.cache_hit_count >= 1
        assert entry.cache_miss_count >= 1


def test_http_hit_miss_parity(servers):
    _, http_runner = servers
    with httpclient.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port) as client:
        inputs = _client_inputs(72, httpclient.InferInput)
        first = client.infer("simple_cache", inputs)
        second = client.infer("simple_cache", inputs)
        _assert_parity(first, second, 72)
        stats = client.get_inference_statistics("simple_cache")
        entry = stats["model_stats"][0]
        assert int(entry["cache_hit_count"]) >= 1
        assert int(entry["cache_miss_count"]) >= 1
        assert int(entry["inference_stats"]["cache_hit"]["count"]) >= 1


def test_grpc_aio_hit_miss_parity(servers):
    grpc_handle, _ = servers

    async def run():
        client = grpcclient_aio.InferenceServerClient(grpc_handle.address)
        try:
            inputs = _client_inputs(73, grpcclient_aio.InferInput)
            first = await client.infer("simple_cache", inputs)
            second = await client.infer("simple_cache", inputs)
            _assert_parity(first, second, 73)
            stats = await client.get_inference_statistics("simple_cache")
            assert stats.model_stats[0].cache_hit_count >= 1
        finally:
            await client.close()

    asyncio.run(run())


def test_http_aio_hit_miss_parity(servers):
    _, http_runner = servers

    async def run():
        client = httpclient_aio.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port)
        try:
            inputs = _client_inputs(74, httpclient_aio.InferInput)
            first = await client.infer("simple_cache", inputs)
            second = await client.infer("simple_cache", inputs)
            _assert_parity(first, second, 74)
            stats = await client.get_inference_statistics("simple_cache")
            assert int(stats["model_stats"][0]["cache_hit_count"]) >= 1
        finally:
            await client.close()

    asyncio.run(run())


def test_config_renders_response_cache_both_transports(servers):
    grpc_handle, http_runner = servers
    with grpcclient.InferenceServerClient(grpc_handle.address) as client:
        config = client.get_model_config("simple_cache", as_json=True)
        config = config.get("config", config)
        assert config["response_cache"]["enable"] is True
    with httpclient.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port) as client:
        config = client.get_model_config("simple_cache")
        assert config["response_cache"]["enable"] is True


def test_perf_parser_composing_cache_caveat():
    """Satellite: the ensemble caveat — a top model with NO cache whose
    composing model enables it must still flip the caveat flag."""
    from client_tpu.perf.client_backend import MockBackend
    from client_tpu.perf.model_parser import ModelParser

    backend = MockBackend(
        model_config_dict={
            "name": "ens",
            "ensemble_scheduling": {"step": [{"model_name": "backbone"}]},
        },
        model_configs={
            "backbone": {"max_batch_size": 4,
                         "response_cache": {"enable": True}},
        },
    )
    model = ModelParser().parse(backend, "ens")
    assert not model.response_cache_enabled
    assert model.composing_cache_enabled
