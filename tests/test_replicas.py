"""Replica-serving tests: per-device fault domains behind the
health-routed in-process router (client_tpu.server.replicas).

Covers the full lifecycle the ISSUE-8 tentpole names: routing spread
under load, watchdog ejection of a hung replica, bounded (exactly
once) re-dispatch of failed batches, supervisor re-initialize + canary
readmission, sticky sequences surviving a sibling's ejection, golden
parity single- vs 4-replica, partial-degradation health/readiness
metadata over both transports, replica-targeted chaos (replica= scope
+ hang_ms faults), and the statistics / Prometheus observability
surface.
"""

import threading
import time

import numpy as np
import pytest

from client_tpu._infer_common import InferInput
from client_tpu.grpc._utils import InferResult, get_inference_request
from client_tpu.models.add_sub import AddSub
from client_tpu.models.simple_extra import SequenceAccumulator
from client_tpu.server import chaos
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.server.replicas import (
    ReplicaSet,
    ReplicatedModel,
    wants_replicas,
)
from client_tpu.utils import InferenceServerException


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.configure(None)
    yield
    chaos.configure(None)


# -- helpers ---------------------------------------------------------------


class _Stub(ServedModel):
    """Minimal host model for router unit tests: OUTPUT = INPUT + tag.
    ``fail`` / ``hang_s`` flip one instance into a fault; ``calls``
    counts executions on this instance."""

    def __init__(self, name="stub", tag=0, delay_s=0.0):
        super().__init__()
        self.name = name
        self.tag = tag
        self.delay_s = delay_s
        self.fail = False
        self.fail_status = "UNAVAILABLE"
        self.hang_s = 0.0
        self.calls = 0
        self.inputs = [TensorSpec("INPUT", "INT32", [1])]
        self.outputs = [TensorSpec("OUTPUT", "INT32", [1])]

    def infer(self, inputs, parameters=None):
        self.calls += 1
        if self.hang_s:
            time.sleep(self.hang_s)
        elif self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise InferenceServerException(
                "stub fault", status=self.fail_status)
        value = int(np.asarray(inputs["INPUT"]).reshape(-1)[0])
        return {"OUTPUT": np.array([value + self.tag], dtype=np.int32)}


def _stub_set(count=4, delay_s=0.0, watchdog_us=500_000,
              failure_threshold=2, recovery_s=0.2):
    instances = []

    def factory():
        instance = _Stub(tag=len(instances), delay_s=delay_s)
        instances.append(instance)
        return instance

    base = factory()
    replica_set = ReplicaSet(base, factory=factory, count=count,
                             watchdog_us=watchdog_us,
                             failure_threshold=failure_threshold,
                             recovery_s=recovery_s)
    return replica_set, instances


def _one(value):
    return {"INPUT": np.array([value], dtype=np.int32)}


def _request(value, model, shape=(1, 16), **kwargs):
    tensors = []
    for name, fill in (("INPUT0", value), ("INPUT1", 2 * value)):
        tensor = InferInput(name, list(shape), "INT32")
        tensor.set_data_from_numpy(np.full(shape, fill, dtype=np.int32))
        tensors.append(tensor)
    return get_inference_request(model_name=model, inputs=tensors,
                                 outputs=None, **kwargs)


def _replica_snapshot(core, name):
    entry = core.model_statistics(name).model_stats[0]
    return entry


def _wait_for(predicate, timeout_s=8.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


# -- chaos: replica targeting + hang_ms ------------------------------------


def test_chaos_spec_parses_replica_and_hang():
    config = chaos.ChaosConfig.from_spec(
        "hang_ms=250,replica=simple:1,seed=3")
    assert config.hang_ms == 250.0
    assert config.replica == "simple:1"
    assert config.enabled
    assert "hangs" in config.describe()
    assert "replica simple:1" in config.describe()


def test_chaos_spec_rejects_bad_replica_target():
    with pytest.raises(ValueError):
        chaos.ChaosConfig.from_spec("replica=notarget")


def test_chaos_replica_targeting_fires_only_in_its_domain():
    chaos.configure(chaos.ChaosConfig(error_rate=1.0, replica="m:1"))
    # Request-level inject (no replica layer): never fires.
    chaos.inject("m")
    # Sibling replica: never fires.
    chaos.inject("m", replica_id="m:0")
    # The targeted replica: always fires.
    with pytest.raises(InferenceServerException):
        chaos.inject("m", replica_id="m:1")


def test_chaos_untargeted_config_skips_replica_layer():
    chaos.configure(chaos.ChaosConfig(error_rate=1.0))
    with pytest.raises(InferenceServerException):
        chaos.inject("m")
    # One fault, one layer: a request-level config must not fire a
    # second time inside the replica that executes the same request.
    chaos.inject("m", replica_id="m:0")


def test_chaos_hang_is_deterministic_and_counted():
    chaos.configure(chaos.ChaosConfig(hang_ms=30, replica="m:0", seed=7))
    t0 = time.monotonic()
    chaos.inject("m", replica_id="m:0")
    assert time.monotonic() - t0 >= 0.025
    assert chaos.stats()["injected_hangs"] == 1


def test_degrade_one_replica_mode_spec():
    kwargs = chaos.DegradeOneScenario.parse_spec(
        "replica=simple:2,kill_after_s=2,kill_kind=hang,heal_after_s=5")
    assert kwargs == {"replica": "simple:2", "kill_after_s": 2.0,
                      "kill_kind": "hang", "heal_after_s": 5.0}
    with pytest.raises(ValueError):
        chaos.DegradeOneScenario.parse_spec("replica=nocolon")
    with pytest.raises(ValueError):
        chaos.DegradeOneScenario(replica="m:0", kill_kind="explode")


def test_degrade_one_replica_mode_stages():
    scenario = chaos.DegradeOneScenario(
        replica="m:1", kill_after_s=0.0, heal_after_s=0.1).start()
    assert scenario.killed.wait(timeout=2.0)
    with pytest.raises(InferenceServerException):
        chaos.inject("m", replica_id="m:1")
    assert scenario.healed.wait(timeout=2.0)
    chaos.inject("m", replica_id="m:1")  # fault cleared
    scenario.stop()


def test_degrade_one_replica_mode_preserves_global_chaos():
    # The replica-mode scenario stages its faults in the dedicated
    # replica slot: an operator's global --chaos config must survive
    # every stage AND the scenario's stop().
    chaos.configure(chaos.ChaosConfig(latency_ms=1, seed=5))
    delayed_before = chaos.stats()["delayed_requests"]
    scenario = chaos.DegradeOneScenario(
        replica="m:1", kill_after_s=0.0, heal_after_s=0.05).start()
    assert scenario.killed.wait(timeout=2.0)
    with pytest.raises(InferenceServerException):
        chaos.inject("m", replica_id="m:1")
    assert scenario.healed.wait(timeout=2.0)
    scenario.stop()
    chaos.inject("m")  # global latency config still active
    assert chaos.stats()["delayed_requests"] > delayed_before


# -- router unit tests -----------------------------------------------------


def test_wants_replicas_gate():
    model = _Stub()
    assert not wants_replicas(model)
    model.instance_group_count = 1
    assert wants_replicas(model)


def test_routing_spread_under_load():
    replica_set, _ = _stub_set(count=4, delay_s=0.005)
    try:
        def loop(index):
            for i in range(20):
                replica_set.infer(_one(index * 100 + i))

        pool = [threading.Thread(target=loop, args=(i,))
                for i in range(8)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        snap = replica_set.snapshot()
        served = [r["execution_count"] for r in snap["replicas"]]
        assert sum(served) == 160
        # Least-expected-completion-time routing must spread a
        # saturating closed loop across every fault domain.
        assert all(count > 0 for count in served)
    finally:
        replica_set.stop()


def test_golden_parity_across_replicas():
    replica_set, instances = _stub_set(count=4)
    try:
        # Every instance computes the same function (tag aside, the
        # stub tags prove WHICH replica served) — here use tag-free
        # parity via a shared-function model instead: all outputs must
        # equal input + tag of some live instance, and a single-replica
        # set must match the base exactly.
        single = ReplicaSet(_Stub(tag=0), count=1)
        try:
            for value in range(10):
                out = single.infer(_one(value))
                assert int(out["OUTPUT"][0]) == value
        finally:
            single.stop()
    finally:
        replica_set.stop()


def test_watchdog_marks_hung_replica_and_redispatches():
    replica_set, instances = _stub_set(count=2, watchdog_us=150_000)
    try:
        victim = replica_set.replicas[0].model
        victim.hang_s = 1.0
        out = replica_set.infer(_one(5))  # re-dispatched to sibling
        assert int(out["OUTPUT"][0]) in (5, 5 + 1)
        snap = replica_set.snapshot()
        assert snap["watchdog_trips"] >= 1
        assert snap["redispatches"] >= 1
        assert snap["ejections"] >= 1
        assert snap["healthy"] == 1
        assert not replica_set.replicas[0].healthy()
    finally:
        victim.hang_s = 0.0
        replica_set.stop()


def test_watchdog_budget_scales_with_queue_depth():
    # Load is not a hang: executions stacked on one replica's
    # single-thread device queue each get one watchdog period per
    # queued predecessor, so a slow-but-healthy replica under burst
    # load is never falsely ejected.
    replica_set, _ = _stub_set(count=1, delay_s=0.15,
                               watchdog_us=250_000)
    try:
        errors = [0]

        def loop(i):
            try:
                replica_set.infer(_one(i))
            except InferenceServerException:
                errors[0] += 1

        pool = [threading.Thread(target=loop, args=(i,))
                for i in range(4)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        # 4 x 150ms serialized = 600ms total; a flat 250ms watchdog
        # would have tripped on the queued waiters.
        assert errors[0] == 0
        snap = replica_set.snapshot()
        assert snap["watchdog_trips"] == 0
        assert snap["healthy"] == 1
    finally:
        replica_set.stop()


def test_redispatch_happens_exactly_once():
    replica_set, instances = _stub_set(count=3, failure_threshold=10)
    try:
        for replica in replica_set.replicas:
            replica.model.fail = True
        calls_before = sum(i.calls for i in instances)
        with pytest.raises(InferenceServerException):
            replica_set.infer(_one(1))
        calls_after = sum(i.calls for i in instances)
        # One dispatch + exactly one re-dispatch, never a storm.
        assert calls_after - calls_before == 2
        assert replica_set.snapshot()["redispatches"] == 1
    finally:
        replica_set.stop()


def test_client_errors_never_redispatch():
    replica_set, instances = _stub_set(count=2)
    try:
        for replica in replica_set.replicas:
            replica.model.fail = True
            replica.model.fail_status = "INVALID_ARGUMENT"
        calls_before = sum(i.calls for i in instances)
        with pytest.raises(InferenceServerException) as err:
            replica_set.infer(_one(1))
        assert err.value.status() == "INVALID_ARGUMENT"
        assert sum(i.calls for i in instances) - calls_before == 1
        assert replica_set.snapshot()["redispatches"] == 0
        # Definitive client errors are health evidence, not failures.
        assert replica_set.snapshot()["healthy"] == 2
    finally:
        replica_set.stop()


def test_breaker_ejects_after_repeated_failures():
    replica_set, _ = _stub_set(count=2, failure_threshold=2,
                               recovery_s=30.0)
    try:
        victim = replica_set.replicas[0]
        victim.model.fail = True
        for i in range(8):
            replica_set.infer(_one(i))  # masked by re-dispatch
        snap = replica_set.snapshot()
        assert snap["ejections"] == 1
        assert snap["healthy"] == 1
        assert not victim.healthy()
        # Ejected replica is out of routing: traffic flows untouched.
        calls = victim.model.calls
        for i in range(5):
            replica_set.infer(_one(i))
        assert victim.model.calls == calls
    finally:
        replica_set.stop()


def test_all_replicas_ejected_is_unavailable():
    replica_set, _ = _stub_set(count=2, failure_threshold=1,
                               recovery_s=30.0)
    try:
        for replica in replica_set.replicas:
            replica.model.fail = True
        with pytest.raises(InferenceServerException):
            replica_set.infer(_one(1))
        with pytest.raises(InferenceServerException) as err:
            replica_set.infer(_one(2))
        assert err.value.status() == "UNAVAILABLE"
        assert "no healthy replica" in str(err.value)
    finally:
        replica_set.stop()


def test_supervisor_reinitializes_and_readmits():
    replica_set, instances = _stub_set(count=2, failure_threshold=2,
                                       recovery_s=0.2)
    try:
        victim = replica_set.replicas[1]
        victim_instance = victim.model
        victim_instance.fail = True
        for i in range(6):
            replica_set.infer(_one(i))
        assert not victim.healthy()
        generation = victim.generation
        # The instance stays poisoned; the supervisor must build a
        # FRESH executable from the factory (weight re-init), canary
        # it, and readmit.
        assert _wait_for(lambda: victim.healthy())
        snap = replica_set.snapshot()
        assert snap["readmissions"] == 1
        assert snap["probes"] >= 1
        assert victim.generation > generation
        assert victim.model is not victim_instance  # fresh weights
        assert replica_set.snapshot()["healthy"] == 2
    finally:
        replica_set.stop()


def test_supervisor_keeps_ejected_while_fault_persists():
    replica_set, instances = _stub_set(count=2, failure_threshold=2,
                                       recovery_s=0.1)
    try:
        # Fault every instance the factory will ever make: canaries
        # must keep failing and the replica must stay out.
        class _AlwaysBad(_Stub):
            def infer(self, inputs, parameters=None):
                raise InferenceServerException("still bad",
                                               status="INTERNAL")

        replica_set._factory = _AlwaysBad
        victim = replica_set.replicas[0]
        victim.model.fail = True
        for i in range(6):
            replica_set.infer(_one(i))
        assert not victim.healthy()
        time.sleep(0.6)  # several probe periods
        assert not victim.healthy()
        assert replica_set.snapshot()["probes"] >= 1
        assert replica_set.snapshot()["readmissions"] == 0
    finally:
        replica_set.stop()


# -- sticky sequences ------------------------------------------------------


def test_sticky_pins_and_releases_on_sequence_end():
    replica_set, _ = _stub_set(count=4, delay_s=0.002)
    try:
        proxy = replica_set.proxy
        assert isinstance(proxy, ReplicatedModel)
        # Saturate the set so least-ECT would otherwise move around.
        noise = [threading.Thread(
            target=lambda i=i: [replica_set.infer(_one(i * 10 + j))
                                for j in range(10)])
            for i in range(4)]
        for thread in noise:
            thread.start()
        pinned = []
        for step in range(6):
            proxy.infer(_one(step), {"sequence_id": 99})
            pinned.append(replica_set.sticky_replica(99))
        for thread in noise:
            thread.join()
        assert len({p for p in pinned}) == 1  # never hopped
        proxy.infer(_one(7), {"sequence_id": 99, "sequence_end": True})
        assert replica_set.sticky_replica(99) is None  # released
    finally:
        replica_set.stop()


def test_sticky_sequence_survives_sibling_ejection():
    instances = []

    def factory():
        instance = SequenceAccumulator(name="seq_replicas")
        instances.append(instance)
        return instance

    base = factory()
    replica_set = ReplicaSet(base, factory=factory, count=3,
                             failure_threshold=1, recovery_s=30.0)
    try:
        proxy = replica_set.proxy
        total = 0

        def step(value, start=False, end=False):
            params = {"sequence_id": 42}
            if start:
                params["sequence_start"] = True
            if end:
                params["sequence_end"] = True
            out = proxy.infer(_one(value), params)
            return int(out["OUTPUT"][0])

        assert step(5, start=True) == 5
        pinned = replica_set.sticky_replica(42)
        assert pinned is not None
        # Eject a SIBLING fault domain mid-sequence.
        sibling = replica_set.replicas[(pinned + 1) % 3]
        replica_set._mark_hung(sibling)
        assert replica_set.snapshot()["healthy"] == 2
        total = step(7)
        assert total == 12  # replica-local state intact
        assert step(3, end=True) == 15
        assert replica_set.sticky_replica(42) is None
    finally:
        replica_set.stop()


def test_sticky_transient_fault_on_healthy_pin_does_not_migrate():
    """A transient (non-ejecting) failure on a still-healthy pinned
    replica must surface the error, NOT re-dispatch the step to a
    sibling: the sequence's replica-local state lives on the pin, and
    a stateless sibling would silently return wrong results."""
    replica_set, _ = _stub_set(count=3, failure_threshold=3)
    try:
        proxy = replica_set.proxy
        proxy.infer(_one(1), {"sequence_id": 7})
        pinned = replica_set.sticky_replica(7)
        assert pinned is not None
        pinned_model = replica_set.replicas[pinned].model
        sibling_models = [r.model for r in replica_set.replicas
                          if r.index != pinned]
        sibling_calls_before = sum(m.calls for m in sibling_models)
        # One transient fault on the pinned replica (threshold 3: the
        # breaker stays closed, the replica stays healthy).
        pinned_model.fail = True
        pinned_model.fail_status = "INTERNAL"
        with pytest.raises(InferenceServerException):
            proxy.infer(_one(2), {"sequence_id": 7})
        pinned_model.fail = False
        # The pin did not migrate, the replica is still healthy, and
        # no sibling executed the faulted step.
        assert replica_set.replicas[pinned].healthy()
        assert replica_set.sticky_replica(7) == pinned
        assert sum(m.calls for m in sibling_models) \
            == sibling_calls_before
        # The retry (client-side semantics) lands back on the pin.
        out = proxy.infer(_one(2), {"sequence_id": 7})
        assert replica_set.sticky_replica(7) == pinned
        assert int(out["OUTPUT"][0]) == 2 + pinned_model.tag
    finally:
        replica_set.stop()


# -- core integration ------------------------------------------------------


@pytest.fixture(scope="module")
def replica_core():
    core = build_core(["simple", "simple_replicas"], warmup=False)
    yield core
    core.shutdown()


def test_golden_parity_single_vs_four_replicas(replica_core):
    core = replica_core
    for value in (0, 1, 7, 96):
        single = InferResult(core.infer(_request(value, "simple",
                                                 shape=(16,))))
        quad = InferResult(core.infer(_request(value, "simple_replicas")))
        np.testing.assert_array_equal(
            single.as_numpy("OUTPUT0").reshape(-1),
            quad.as_numpy("OUTPUT0").reshape(-1))
        np.testing.assert_array_equal(
            single.as_numpy("OUTPUT1").reshape(-1),
            quad.as_numpy("OUTPUT1").reshape(-1))


def test_fused_batches_route_across_replicas(replica_core):
    core = replica_core

    def loop(index):
        for i in range(25):
            core.infer(_request(index * 100 + i, "simple_replicas"))

    pool = [threading.Thread(target=loop, args=(i,)) for i in range(8)]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()
    entry = _replica_snapshot(core, "simple_replicas")
    assert entry.total_replicas == 4
    assert entry.healthy_replicas == 4
    per_replica = sum(int(r.execution_count) for r in entry.replica_stats)
    # Every fused execution the batcher dispatched ran on exactly one
    # replica's device queue.
    assert per_replica == int(entry.execution_count)
    active = sum(1 for r in entry.replica_stats if r.execution_count)
    assert active >= 2


def test_replica_kill_masked_health_and_readmission(replica_core):
    core = replica_core
    errors = [0]
    chaos.configure(chaos.ChaosConfig(error_rate=1.0,
                                      replica="simple_replicas:1"))

    def loop(index):
        for i in range(40):
            try:
                core.infer(_request(index * 1000 + i, "simple_replicas"))
            except InferenceServerException:
                errors[0] += 1

    pool = [threading.Thread(target=loop, args=(i,)) for i in range(8)]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()
    # Blast radius is ONE fault domain: zero client-visible errors.
    assert errors[0] == 0

    def ejected_total():
        entry = _replica_snapshot(core, "simple_replicas")
        return sum(int(r.ejected_count) for r in entry.replica_stats)

    # The batcher fuses those 320 requests into a NONDETERMINISTIC
    # number of executions (preferred_batch_sizes=[4] under 8 racing
    # threads), so a quiet run can finish with fewer than
    # failure_threshold fused batches ever landing on the poisoned
    # replica — its breaker never trips and ejected stays 0 (the
    # pre-PR-17 flake, observed on the seed tree too). Chaos is still
    # active, so keep feeding masked singles until the breaker has
    # provably tripped: replica 1's EWMA stays 0 (failures never
    # update it), which makes it the router's first choice, and each
    # injected fault is masked by the bounded redispatch against a
    # healthy sibling — these extra requests cannot fail client-
    # visibly.
    fill = iter(range(100_000, 200_000))
    deadline = time.monotonic() + 8.0
    while ejected_total() < 1:
        assert time.monotonic() < deadline, \
            "poisoned replica's breaker never tripped"
        core.infer(_request(next(fill), "simple_replicas"))
    entry = _replica_snapshot(core, "simple_replicas")
    assert entry.healthy_replicas == 3
    # Partial degradation: the model (and server) stay ready, and the
    # metadata names the degraded fleet.
    assert core.model_ready("simple_replicas")
    assert core.server_ready()
    assert core.replica_health("simple_replicas") == (3, 4)
    # Heal: the supervisor re-initializes, canaries, readmits.
    chaos.configure(None)
    assert _wait_for(
        lambda: core.replica_health("simple_replicas") == (4, 4))
    entry = _replica_snapshot(core, "simple_replicas")
    assert sum(int(r.readmitted_count) for r in entry.replica_stats) >= 1


def test_hang_fault_caught_by_watchdog_e2e():
    core = build_core([], warmup=False)
    try:
        def factory():
            model = AddSub(name="hang_replicas", datatype="INT32",
                           shape=(16,))
            model.instance_group_count = 2
            model.replica_watchdog_us = 200_000
            model.replica_failure_threshold = 5
            model.replica_recovery_s = 30.0
            return model

        core.repository.add_factory("hang_replicas", factory)
        core.repository.load("hang_replicas")
        core.infer(_request(1, "hang_replicas", shape=(16,)))
        chaos.configure(chaos.ChaosConfig(hang_ms=1500,
                                          replica="hang_replicas:0"))
        errors = [0]

        def loop(index):
            for i in range(12):
                try:
                    core.infer(_request(index * 100 + i,
                                        "hang_replicas", shape=(16,)))
                except InferenceServerException:
                    errors[0] += 1

        pool = [threading.Thread(target=loop, args=(i,))
                for i in range(4)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        # The watchdog bounds the hang and re-dispatch masks it.
        assert errors[0] == 0
        entry = _replica_snapshot(core, "hang_replicas")
        assert entry.healthy_replicas == 1
        assert sum(int(r.ejected_count)
                   for r in entry.replica_stats) >= 1
    finally:
        chaos.configure(None)
        core.shutdown()


def test_full_ejection_flips_model_not_ready():
    core = build_core([], warmup=False)
    try:
        def factory():
            model = AddSub(name="tiny_replicas", datatype="INT32",
                           shape=(16,))
            model.instance_group_count = 2
            model.replica_failure_threshold = 1
            model.replica_recovery_s = 30.0
            return model

        core.repository.add_factory("tiny_replicas", factory)
        core.repository.load("tiny_replicas")
        core.infer(_request(1, "tiny_replicas", shape=(16,)))
        assert core.model_ready("tiny_replicas")
        replica_set = core._replica_sets["tiny_replicas"]
        for replica in replica_set.replicas:
            replica_set._mark_hung(replica)
        # Full-model ejection: not ready; the server itself stays up.
        assert not core.model_ready("tiny_replicas")
        assert core.server_ready()
        assert core.replica_health("tiny_replicas") == (0, 2)
        with pytest.raises(InferenceServerException):
            core.infer(_request(2, "tiny_replicas", shape=(16,)))
    finally:
        core.shutdown()


def test_unload_drains_replica_set():
    core = build_core(["simple_replicas"], warmup=False)
    try:
        core.infer(_request(1, "simple_replicas"))
        assert "simple_replicas" in core._replica_sets
        supervisor = core._replica_sets["simple_replicas"]._supervisor
        core.unload_model("simple_replicas")
        assert "simple_replicas" not in core._replica_sets
        assert not supervisor.is_alive()
        # Reload serves again with a fresh replica set.
        core.load_model("simple_replicas")
        core.infer(_request(2, "simple_replicas"))
        assert core.replica_health("simple_replicas") == (4, 4)
    finally:
        core.shutdown()


def test_prometheus_replica_families(replica_core):
    core = replica_core
    core.infer(_request(3, "simple_replicas"))
    text = core.metrics_text()
    assert 'tpu_replica_healthy{model="simple_replicas"}' in text
    assert 'tpu_replica_count{model="simple_replicas"} 4' in text
    assert "tpu_replica_ejected_total" in text
    assert "tpu_replica_readmitted_total" in text
    assert "tpu_replica_redispatch_total" in text
    assert 'tpu_replica_exec_us{model="simple_replicas",replica="0"}' \
        in text
    # HELP/TYPE precede samples for every replica family.
    lines = text.splitlines()
    for family in ("tpu_replica_healthy", "tpu_replica_ejected_total",
                   "tpu_replica_exec_us"):
        type_at = next(i for i, l in enumerate(lines)
                       if l.startswith("# TYPE %s " % family))
        sample_at = next(i for i, l in enumerate(lines)
                         if l.startswith(family))
        assert type_at < sample_at


def test_model_config_renders_instance_group(replica_core):
    config = replica_core.model_config("simple_replicas").config
    assert len(config.instance_group) == 1
    group = config.instance_group[0]
    assert group.count == 4
    assert group.kind == 2  # KIND_CPU


def test_ready_metadata_over_http(replica_core):
    import urllib.request

    from client_tpu.server.http_server import start_http_server_thread

    runner = start_http_server_thread(replica_core, host="127.0.0.1",
                                      port=0)
    try:
        replica_core.infer(_request(5, "simple_replicas"))
        url = ("http://127.0.0.1:%d/v2/models/simple_replicas/ready"
               % runner.port)
        with urllib.request.urlopen(url, timeout=5) as response:
            assert response.status == 200
            assert response.headers["x-replica-total"] == "4"
            assert int(response.headers["x-replica-healthy"]) >= 1
        # Non-replicated models carry no replica metadata.
        url = "http://127.0.0.1:%d/v2/models/simple/ready" % runner.port
        with urllib.request.urlopen(url, timeout=5) as response:
            assert response.status == 200
            assert response.headers.get("x-replica-total") is None
    finally:
        runner.stop()


def test_ready_metadata_over_grpc(replica_core):
    import grpc

    from client_tpu.protocol import inference_pb2 as pb
    from client_tpu.protocol.service import GRPCInferenceServiceStub

    handle = start_grpc_server(core=replica_core,
                               address="127.0.0.1:0")
    try:
        replica_core.infer(_request(6, "simple_replicas"))
        channel = grpc.insecure_channel(handle.address)
        stub = GRPCInferenceServiceStub(channel)
        response, call = stub.ModelReady.with_call(
            pb.ModelReadyRequest(name="simple_replicas"))
        assert response.ready
        trailing = {k: v for k, v in call.trailing_metadata()}
        assert trailing.get("replica-total") == "4"
        assert int(trailing.get("replica-healthy", "0")) >= 1
        channel.close()
    finally:
        handle.stop()
