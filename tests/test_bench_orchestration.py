"""bench.py orchestration branches end to end (monkeypatched children).

The driver's headline number rides main()'s retry/merge/labeling flow;
these tests run the REAL main() with run_child faked, pinning the four
scenarios the relay can produce: clean TPU, whole-run CPU fallback
with a successful TPU retry, a mid-run wedge recovered by a TPU retry,
and a persistent wedge supplemented on CPU."""

import importlib.util
import json
import pathlib

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture()
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location("bench_o", REPO / "bench.py")
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    monkeypatch.setattr(module, "build_native_harness", lambda deadline_s: True)
    # The native-serving phase launches a real tpu_serverd; tests pin
    # the orchestration flow, so record the invocation instead.
    module.native_serving_calls = []
    monkeypatch.setattr(
        module, "run_native_serving_supplement",
        lambda result, deadline_ts:
            module.native_serving_calls.append(result.get("platform")))
    monkeypatch.setenv("BENCH_BUDGET_S", "1500")
    module.T0 = __import__("time").time()  # fresh budget window
    return module


def run_main(bench, capsys, children):
    """Feed main() a scripted sequence of child results; returns the
    printed JSON line and the calls run_child received."""
    calls = []

    def fake_run_child(platform, init_deadline_s, deadline_ts,
                       skip_stages=None):
        calls.append({"platform": platform,
                      "skip": sorted(skip_stages or [])})
        assert deadline_ts > __import__("time").time()
        return children.pop(0) if children else None

    bench.run_child = fake_run_child
    bench.main()
    out = [line for line in capsys.readouterr().out.splitlines() if line][-1]
    return json.loads(out), calls


def stage(tput, **extra):
    return dict({"throughput": tput, "p50_latency_us": 1000.0}, **extra)


def test_clean_tpu_run_single_child(bench, capsys):
    result, calls = run_main(bench, capsys, [{
        "platform": "tpu", "device_probe": "ok",
        "stages": {
            "simple_grpc": stage(2000.0, vs_baseline=1.4),
            "resnet50_tpu_shm_grpc": stage(2100.0, vs_baseline=12.7,
                                           mfu_device=0.14),
            "bert_grpc_sysshm": stage(600.0),
            "ensemble_stream_grpc": stage(140.0),
            "resnet50_inprocess": stage(90.0),
            "llm_generate_stream": stage(26.0),
        },
    }])
    assert len(calls) == 1 and calls[0]["platform"] == ""
    assert result["metric"] == "resnet50_tpu_shm_grpc_batch8_c4_infer_per_sec"
    assert result["value"] == 2100.0
    assert result["platform"] == "tpu"
    assert result["stages"]["resnet50_tpu_shm_grpc"]["mfu_device"] == 0.14


def test_whole_cpu_fallback_then_tpu_retry_merges(bench, capsys):
    result, calls = run_main(bench, capsys, [
        None,  # attempt 1: init deadline missed
        {"platform": "cpu", "stages": {
            "simple_grpc": stage(1200.0, vs_baseline=0.85),
            "resnet50_tpu_shm_grpc": stage(10.0, vs_baseline=0.06,
                                           mfu_device=0.1),
        }},
        {"platform": "tpu", "device_probe": "ok", "stages": {
            "resnet50_tpu_shm_grpc": stage(2000.0, vs_baseline=12.0),
        }},
    ])
    assert [c["platform"] for c in calls] == ["", "cpu", ""]
    # TPU retry stage under its true name wins the headline...
    assert result["metric"] == "resnet50_tpu_shm_grpc_batch8_c4_infer_per_sec"
    assert result["value"] == 2000.0
    # ...the CPU resnet is suffixed and stripped of every TPU anchor...
    fallback = result["stages"]["resnet50_tpu_shm_grpc_cpu_fallback"]
    assert fallback == {"throughput": 10.0, "p50_latency_us": 1000.0}
    # ...and the host-placed simple keeps its name and anchor.
    assert result["stages"]["simple_grpc"]["vs_baseline"] == 0.85


def test_wedged_probe_retries_missing_stages_on_tpu(bench, capsys):
    result, calls = run_main(bench, capsys, [
        {"platform": "tpu", "device_probe": "stalled: relay wedged",
         "stages": {"simple_grpc": stage(2000.0, vs_baseline=1.4)}},
        {"platform": "tpu", "device_probe": "ok", "stages": {
            "resnet50_tpu_shm_grpc": stage(1900.0, vs_baseline=11.5),
            "resnet50_inprocess": stage(90.0),
            "bert_grpc_sysshm": stage(600.0),
            "ensemble_stream_grpc": stage(140.0),
            "llm_generate_stream": stage(26.0),
        }},
    ])
    assert [c["platform"] for c in calls] == ["", ""]
    # retry skipped the already-measured host stage
    assert calls[1]["skip"] == ["simple_grpc"]
    assert result["value"] == 1900.0
    assert result["stages"]["resnet50_tpu_shm_grpc"]["vs_baseline"] == 11.5
    assert "resnet50_tpu_shm_grpc_cpu_fallback" not in result["stages"]


def test_persistent_wedge_supplements_on_cpu(bench, capsys):
    wedged = {"platform": "tpu", "device_probe": "stalled: relay wedged",
              "stages": {"simple_grpc": stage(2000.0, vs_baseline=1.4)}}
    result, calls = run_main(bench, capsys, [
        wedged,
        dict(wedged, stages={}),  # TPU retry: still wedged, nothing new
        {"platform": "cpu", "stages": {
            "resnet50_tpu_shm_grpc": stage(10.0, vs_baseline=0.06),
            "bert_grpc_sysshm": stage(5.0, vs_baseline=0.05),
        }},
    ])
    assert [c["platform"] for c in calls] == ["", "", "cpu"]
    # headline never uses a cpu_fallback TPU-named stage: the
    # host-placed native-server stage is absent, so simple_grpc leads.
    assert result["metric"] == "simple_grpc_c4_infer_per_sec"
    assert result["value"] == 2000.0
    assert result["stages"]["resnet50_tpu_shm_grpc_cpu_fallback"] == {
        "throughput": 10.0, "p50_latency_us": 1000.0}
    assert "bert_grpc_sysshm" not in result["stages"]
    assert "bert_grpc_sysshm_cpu_fallback" in result["stages"]


def test_native_serving_supplement_runs_only_on_clean_tpu(bench, capsys):
    run_main(bench, capsys, [{
        "platform": "tpu", "device_probe": "ok",
        "stages": {
            "simple_grpc": stage(2000.0, vs_baseline=1.4),
            "resnet50_tpu_shm_grpc": stage(2100.0, vs_baseline=12.7),
        },
    }])
    assert bench.native_serving_calls == ["tpu"]


def test_native_serving_supplement_skipped_on_cpu(bench, capsys):
    run_main(bench, capsys, [
        None,  # TPU attempt produced nothing
        {"platform": "cpu", "stages": {
            "simple_grpc": stage(1500.0, vs_baseline=1.1)}},
        None,  # TPU retry after fallback: still nothing
    ])
    assert bench.native_serving_calls == []


def test_native_serving_stage_takes_headline(bench, capsys):
    """When the native-front-end stage exists it outranks the
    Python-front-end stage for the headline."""
    result, _ = run_main(bench, capsys, [{
        "platform": "tpu", "device_probe": "ok",
        "stages": {
            "resnet50_tpu_shm_grpc": stage(2100.0, vs_baseline=12.7),
            "resnet50_tpu_shm_native_server": stage(7700.0,
                                                    vs_baseline=46.4),
        },
    }])
    assert result["metric"] == "resnet50_tpu_shm_native_batch8_c4_infer_per_sec"
    assert result["value"] == 7700.0
