"""Cross-host DCN pull path (docs/cross_host_arena.md rule 2).

Two real processes play two hosts: the OWNER process ("host B") runs a
server whose arena holds typed tensors; this test process ("host A")
redeems B's region handle — first by a direct consumer-side pull into a
local arena, then through the full serving path (a host-A client
registers the B handle with the A server, which pulls transparently and
serves the inference locally).

Replaces the reference's single-host CUDA-IPC sharing contract
(reference src/c++/perf_analyzer/infer_data_manager_shm.h:56) with a
handle-redemption model that crosses hosts."""

import json
import os
import pathlib
import signal
import subprocess
import sys

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.arena_pull import foreign_owner_url, pull_region
from client_tpu.server.tpu_arena import TpuArena
from client_tpu.utils import InferenceServerException

REPO = pathlib.Path(__file__).resolve().parents[1]

# The owner host: serves an arena whose region holds a typed layout —
# two INT32 [16] tensors (the `simple` model's inputs), a BYTES tensor,
# and a raw byte run.
OWNER_SCRIPT = r"""
import json, signal
import numpy as np
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.utils import serialize_byte_tensor

core = build_core([], warmup=False)
handle = start_grpc_server(core=core)
arena = core.memory.arena
raw = arena.create_region(8192, 0)
region_id = json.loads(raw)["region_id"]
rng = np.random.default_rng(7)
x = rng.integers(0, 100, size=16).astype(np.int32)
y = rng.integers(0, 100, size=16).astype(np.int32)
arena.write(region_id, 0, x.tobytes(), "INT32", [16])
arena.write(region_id, 64, y.tobytes(), "INT32", [16])
arr = np.array([b"alpha", b"bravo!"], dtype=np.object_)
arena.write(region_id, 4096, serialize_byte_tensor(arr).tobytes(),
            "BYTES", [2])
arena.write(region_id, 6000, b"\x01\x02\x03\x04")
empty = arena.create_region(512, 0)
print(json.dumps({"address": handle.address, "handle": raw.decode(),
                  "empty_handle": empty.decode(),
                  "x": x.tolist(), "y": y.tolist()}), flush=True)
signal.sigwait([signal.SIGTERM])
handle.stop()
"""


@pytest.fixture(scope="module")
def owner():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    env.pop("CLIENT_TPU_ARENA_URL", None)  # hermetic owner route
    proc = subprocess.Popen(
        [sys.executable, "-c", OWNER_SCRIPT], stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, cwd=str(REPO), env=env)
    try:
        line = proc.stdout.readline().strip()
        assert line, "owner process died before publishing its handle"
        info = json.loads(line)
        yield info
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()


def test_handle_carries_owner_route(owner):
    descriptor = json.loads(owner["handle"])
    assert descriptor["owner_url"] == owner["address"]
    assert foreign_owner_url(owner["handle"].encode(), "someother") \
        == owner["address"]
    # local handles are never routed back out
    assert foreign_owner_url(owner["handle"].encode(),
                             descriptor["arena_id"]) is None


def test_direct_pull_reproduces_typed_layout(owner):
    """Consumer-side pull: the local replica reproduces the owner's
    segments typed — INT32 tensors resolve through the zero-copy
    fast path, BYTES and raw runs survive byte-exact."""
    arena = TpuArena()
    local_handle = pull_region(owner["address"], owner["handle"].encode(),
                               arena)
    descriptor = json.loads(local_handle)
    assert descriptor["arena_id"] == arena.arena_id
    region_id = descriptor["region_id"]
    x = np.asarray(arena.as_typed_array(region_id, 0, 64, "INT32", [16]))
    y = np.asarray(arena.as_typed_array(region_id, 64, 64, "INT32", [16]))
    np.testing.assert_array_equal(x, np.array(owner["x"], np.int32))
    np.testing.assert_array_equal(y, np.array(owner["y"], np.int32))
    bts = arena.as_typed_array(region_id, 4096, 0, "BYTES", [2])
    assert list(bts) == [b"alpha", b"bravo!"]
    assert arena.read(region_id, 6000, 4) == b"\x01\x02\x03\x04"


def test_small_chunks_stream_in_order(owner):
    """Chunked streaming: a 16-byte chunk size forces multi-chunk
    segments; device-side assembly must still be byte-exact."""
    arena = TpuArena()
    local_handle = pull_region(owner["address"], owner["handle"].encode(),
                               arena, chunk_bytes=16)
    region_id = json.loads(local_handle)["region_id"]
    x = np.asarray(arena.as_typed_array(region_id, 0, 64, "INT32", [16]))
    np.testing.assert_array_equal(x, np.array(owner["x"], np.int32))
    bts = arena.as_typed_array(region_id, 4096, 0, "BYTES", [2])
    assert list(bts) == [b"alpha", b"bravo!"]


def test_tampered_handle_is_rejected(owner):
    descriptor = json.loads(owner["handle"])
    descriptor["nonce"] = "0" * 16
    arena = TpuArena()
    with pytest.raises(InferenceServerException):
        pull_region(owner["address"], json.dumps(descriptor).encode(),
                    arena)
    assert arena.list_regions() == []  # failed pull leaks nothing


def test_server_redeems_foreign_handle_end_to_end(owner):
    """The full flow: host-A client registers a host-B handle with the
    host-A server; the server pulls the region over DCN and serves an
    inference from the local replica; unregistration frees it."""
    core = build_core(["simple"], warmup=False)
    handle = start_grpc_server(core=core)
    try:
        with grpcclient.InferenceServerClient(handle.address) as client:
            client.register_tpu_shared_memory(
                "xhost", owner["handle"].encode(), 0, 8192)
            status = client.get_tpu_shared_memory_status()
            assert "xhost" in status.regions

            inputs = [
                grpcclient.InferInput("INPUT0", [16], "INT32"),
                grpcclient.InferInput("INPUT1", [16], "INT32"),
            ]
            inputs[0].set_shared_memory("xhost", 64, offset=0)
            inputs[1].set_shared_memory("xhost", 64, offset=64)
            result = client.infer("simple", inputs)
            x = np.array(owner["x"], np.int32)
            y = np.array(owner["y"], np.int32)
            np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), x + y)
            np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), x - y)

            # The pulled replica is server-owned: unregistering it
            # frees the local HBM region.
            replicas = len(core.memory.arena.list_regions())
            assert replicas >= 1
            client.unregister_tpu_shared_memory("xhost")
            assert len(core.memory.arena.list_regions()) == replicas - 1
    finally:
        handle.stop()


def test_pull_empty_region(owner):
    """A region with no writes yet pulls as an empty, correctly-sized
    replica (the stream's metadata-only chunk)."""
    arena = TpuArena()
    local_handle = pull_region(owner["address"],
                               owner["empty_handle"].encode(), arena)
    descriptor = json.loads(local_handle)
    assert descriptor["byte_size"] == 512
    region_id = descriptor["region_id"]
    assert arena.read(region_id, 0, 16) == b"\x00" * 16  # zero-filled


def test_concurrent_pulls_are_independent(owner):
    """Two consumers redeeming the same handle concurrently each get
    their own coherent replica."""
    import concurrent.futures

    def one_pull(_):
        arena = TpuArena()
        local = pull_region(owner["address"], owner["handle"].encode(),
                            arena)
        region_id = json.loads(local)["region_id"]
        return np.asarray(
            arena.as_typed_array(region_id, 0, 64, "INT32", [16]))

    with concurrent.futures.ThreadPoolExecutor(4) as pool:
        results = list(pool.map(one_pull, range(4)))
    for got in results:
        np.testing.assert_array_equal(got, np.array(owner["x"], np.int32))


def test_http_client_redeems_foreign_handle(owner):
    """Same transparent redemption through the HTTP front-end: the
    registration verb is protocol-symmetric (reference exposes
    register_cuda_shared_memory on both protocols)."""
    import client_tpu.http as httpclient
    from client_tpu.server.http_server import start_http_server_thread

    core = build_core(["simple"], warmup=False)
    runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    try:
        client = httpclient.InferenceServerClient(
            "127.0.0.1:%d" % runner.port)
        client.register_tpu_shared_memory(
            "xh_http", owner["handle"].encode(), 0, 8192)
        status = client.get_tpu_shared_memory_status()
        assert "xh_http" in {r["name"] for r in status}
        inputs = [
            httpclient.InferInput("INPUT0", [16], "INT32"),
            httpclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_shared_memory("xh_http", 64, offset=0)
        inputs[1].set_shared_memory("xh_http", 64, offset=64)
        result = client.infer("simple", inputs)
        x = np.array(owner["x"], np.int32)
        y = np.array(owner["y"], np.int32)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), x + y)
        client.unregister_tpu_shared_memory("xh_http")
        client.close()
    finally:
        runner.stop()


def test_unroutable_foreign_handle_still_rejected(owner):
    """A foreign handle WITHOUT routing info keeps the old error: the
    pull path only engages when the handle says where to pull from."""
    descriptor = json.loads(owner["handle"])
    del descriptor["owner_url"]
    core = build_core([], warmup=False)
    handle = start_grpc_server(core=core)
    try:
        with grpcclient.InferenceServerClient(handle.address) as client:
            with pytest.raises(InferenceServerException) as exc:
                client.register_tpu_shared_memory(
                    "nr", json.dumps(descriptor).encode(), 0, 8192)
            assert exc.value.status() == "INVALID_ARGUMENT"
    finally:
        handle.stop()
