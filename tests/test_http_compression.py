"""Per-call HTTP body compression (gzip/deflate on request and
response), mirroring the reference HTTP client's
request/response_compression_algorithm args (http_client.cc:2130-2247).
"""

import gzip
import zlib

import numpy as np
import pytest

import client_tpu.http as httpclient
from client_tpu.protocol.http_wire import compress_body, decompress_body


@pytest.fixture(scope="module")
def http_server():
    from client_tpu.server.app import build_core
    from client_tpu.server.http_server import start_http_server_thread

    core = build_core(["simple"])
    runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield "127.0.0.1:%d" % runner.port
    runner.stop()


def _make_inputs():
    in0 = np.arange(16, dtype=np.int32)
    in1 = np.ones(16, dtype=np.int32)
    inputs = [
        httpclient.InferInput("INPUT0", [16], "INT32"),
        httpclient.InferInput("INPUT1", [16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(in0)
    inputs[1].set_data_from_numpy(in1)
    return inputs, in0, in1


def test_body_helpers_round_trip():
    payload = b"x" * 4096
    assert decompress_body(compress_body(payload, "gzip"), "gzip") == payload
    assert decompress_body(
        compress_body(payload, "deflate"), "deflate") == payload
    assert gzip.decompress(compress_body(payload, "gzip")) == payload
    assert zlib.decompress(compress_body(payload, "deflate")) == payload
    assert decompress_body(payload, None) == payload
    assert decompress_body(payload, "identity") == payload


@pytest.mark.parametrize("algorithm", ["gzip", "deflate"])
def test_request_compression_round_trip(http_server, algorithm):
    with httpclient.InferenceServerClient(http_server) as client:
        inputs, in0, in1 = _make_inputs()
        result = client.infer(
            "simple", inputs,
            request_compression_algorithm=algorithm)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), in0 - in1)


@pytest.mark.parametrize("algorithm", ["gzip", "deflate"])
def test_response_compression_round_trip(http_server, algorithm):
    with httpclient.InferenceServerClient(http_server) as client:
        inputs, in0, in1 = _make_inputs()
        result = client.infer(
            "simple", inputs,
            response_compression_algorithm=algorithm)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)


def test_accept_encoding_token_parsing():
    from client_tpu.server.http_server import _pick_encoding

    assert _pick_encoding("gzip") == "gzip"
    assert _pick_encoding("deflate, gzip") == "deflate"
    assert _pick_encoding("identity, gzip;q=0") is None  # refused
    assert _pick_encoding("gzip;q=0.5, deflate;q=0") == "gzip"
    assert _pick_encoding("br") is None  # unsupported coding
    assert _pick_encoding("") is None
    assert _pick_encoding("GZIP") == "gzip"  # codings are case-insensitive


def test_both_directions_compressed(http_server):
    with httpclient.InferenceServerClient(http_server) as client:
        inputs, in0, in1 = _make_inputs()
        result = client.infer(
            "simple", inputs,
            request_compression_algorithm="gzip",
            response_compression_algorithm="deflate")
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), in0 - in1)
