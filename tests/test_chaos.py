"""Fault-injection harness tests: chaos spec parsing, deterministic
injection, and the --chaos perf-harness smoke run (the regression gate
for "degrades gracefully")."""

import threading
import time

import numpy as np
import pytest

from client_tpu import robust
from client_tpu.server import chaos
from client_tpu.utils import InferenceServerException


@pytest.fixture(autouse=True)
def clean_chaos():
    yield
    chaos.configure(None)
    robust.reset_retry_total()


def test_spec_parsing():
    config = chaos.ChaosConfig.from_spec(
        "latency_ms=50,error_rate=0.1,drop_rate=0.01,seed=7,models=a+b")
    assert config.latency_ms == 50.0
    assert config.error_rate == 0.1
    assert config.drop_rate == 0.01
    assert config.seed == 7
    assert config.models == {"a", "b"}
    assert config.enabled
    assert not chaos.ChaosConfig.from_spec("").enabled


def test_spec_unknown_key_fails_loudly():
    with pytest.raises(ValueError):
        chaos.ChaosConfig.from_spec("latency=50")
    with pytest.raises(ValueError):
        chaos.ChaosConfig.from_spec("garbage")


def test_inject_error_rate_deterministic():
    chaos.configure(chaos.ChaosConfig(error_rate=1.0, seed=1))
    with pytest.raises(InferenceServerException) as excinfo:
        chaos.inject("m")
    assert excinfo.value.status() == "UNAVAILABLE"
    assert chaos.stats()["injected_errors"] == 1
    # same seed, same outcome sequence
    chaos.configure(chaos.ChaosConfig(error_rate=0.5, seed=42))
    outcomes_a = []
    for _ in range(20):
        try:
            chaos.inject("m")
            outcomes_a.append(True)
        except InferenceServerException:
            outcomes_a.append(False)
    chaos.configure(chaos.ChaosConfig(error_rate=0.5, seed=42))
    outcomes_b = []
    for _ in range(20):
        try:
            chaos.inject("m")
            outcomes_b.append(True)
        except InferenceServerException:
            outcomes_b.append(False)
    assert outcomes_a == outcomes_b
    assert False in outcomes_a and True in outcomes_a


def test_inject_drop_is_distinguishable():
    chaos.configure(chaos.ChaosConfig(drop_rate=1.0, seed=3))
    with pytest.raises(chaos.ChaosDropError):
        chaos.inject("m")
    assert chaos.stats()["injected_drops"] == 1
    # still an InferenceServerException for paths that can't sever TCP
    assert issubclass(chaos.ChaosDropError, InferenceServerException)


def test_inject_latency_and_model_filter():
    chaos.configure(chaos.ChaosConfig(latency_ms=30, seed=2,
                                      models={"slow"}))
    start = time.monotonic()
    chaos.inject("other")  # filtered: no delay
    assert time.monotonic() - start < 0.02
    start = time.monotonic()
    chaos.inject("slow")
    assert time.monotonic() - start >= 0.025
    assert chaos.stats()["delayed_requests"] == 1


def test_disabled_is_noop():
    chaos.configure(None)
    chaos.inject("anything")  # must not raise or sleep
    assert chaos.stats() == {"injected_errors": 0, "injected_drops": 0,
                             "delayed_requests": 0, "injected_hangs": 0,
                             "abandoned_requests": 0}


def test_core_counts_injected_errors_as_failures():
    from client_tpu.server.app import build_core
    from client_tpu.grpc._utils import get_inference_request

    import client_tpu.grpc as grpcclient

    core = build_core(["simple"])
    try:
        inputs = [grpcclient.InferInput("INPUT0", [16], "INT32"),
                  grpcclient.InferInput("INPUT1", [16], "INT32")]
        inputs[0].set_data_from_numpy(np.arange(16, dtype=np.int32))
        inputs[1].set_data_from_numpy(np.ones(16, dtype=np.int32))
        request = get_inference_request(model_name="simple", inputs=inputs)
        chaos.configure(chaos.ChaosConfig(error_rate=1.0, seed=9))
        with pytest.raises(InferenceServerException):
            core.infer(request)
        chaos.configure(None)
        core.infer(request)  # healthy again once chaos is off
        stats = core.model_statistics("simple")
        assert stats.model_stats[0].inference_stats.fail.count == 1
        assert stats.model_stats[0].inference_stats.success.count == 1
    finally:
        core.shutdown()


def test_chaos_smoke_perf_harness(capsys):
    """The regression-gated chaos claim: under injected faults at
    concurrency 4, retries recover >= 90% of retryable failures, no
    request hangs (the run completes), and the report shows the
    recovery."""
    from client_tpu.perf.cli import run

    rc = run([
        "-m", "simple", "--service-kind", "inprocess",
        "--request-count", "40", "-p", "4000",
        "--concurrency-range", "4",
        "--chaos", "error_rate=0.25,seed=11",
        "--retries", "4",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Chaos summary" in out
    assert "client retries:" in out
    # parse the recovery line: "recovered R/F injected faults"
    recovered_line = [line for line in out.splitlines()
                      if "recovered" in line]
    assert recovered_line, out
    fraction = recovered_line[0].split("recovered ")[1].split(" ")[0]
    recovered, faults = (int(x) for x in fraction.split("/"))
    assert faults > 0, "chaos must actually inject faults"
    assert recovered >= 0.9 * faults, out


def test_chaos_smoke_with_bounded_queue():
    """Chaos + saturation end to end in-process: bounded queue sheds
    load (nonzero rejects), nothing hangs, and retries recover the
    rejections."""
    from client_tpu.server.app import build_core
    from tests.test_robustness import SlowBatchModel, _flood, _slow_inputs
    from client_tpu.perf.client_backend import InProcessBackend

    import client_tpu.grpc as grpcclient

    core = build_core([])
    core.repository.add_model(SlowBatchModel(delay_s=0.15,
                                             name="slow_chaos"))
    chaos.configure(chaos.ChaosConfig(error_rate=0.1, latency_ms=20,
                                      seed=13))
    robust.reset_retry_total()
    policy = robust.RetryPolicy(max_attempts=10, initial_backoff_s=0.05,
                                max_backoff_s=0.5)
    backend = InProcessBackend(core, retry_policy=policy)
    try:
        ok, outcomes, hung = _flood(
            lambda: backend.infer("slow_chaos", _slow_inputs(grpcclient)),
            10)
        assert hung == 0, "zero hung requests under fault"
        stats = core.model_statistics("slow_chaos")
        assert stats.model_stats[0].reject_count > 0, \
            "2x-saturation load must hit the bounded queue"
        assert robust.retry_total() > 0
        # >= 90% of requests recovered via retries
        assert ok >= 9, outcomes
    finally:
        backend.close()
        chaos.configure(None)
        core.shutdown()
