"""asyncio client tests (grpc.aio + http.aio) against live servers."""

import asyncio

import numpy as np
import pytest

import client_tpu.grpc.aio as grpcclient_aio
import client_tpu.http.aio as httpclient_aio
from client_tpu._infer_common import InferInput
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.http_server import start_http_server_thread
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def servers():
    core = build_core(["simple"])
    grpc_handle = start_grpc_server(core=core)
    http_runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield grpc_handle, http_runner
    http_runner.stop()
    grpc_handle.stop()


def _inputs():
    in0 = np.arange(16, dtype=np.int32)
    in1 = np.ones(16, dtype=np.int32)
    inputs = [
        InferInput("INPUT0", [16], "INT32"),
        InferInput("INPUT1", [16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(in0)
    inputs[1].set_data_from_numpy(in1)
    return in0, in1, inputs


def test_grpc_aio_basic(servers):
    grpc_handle, _ = servers

    async def run():
        async with grpcclient_aio.InferenceServerClient(
            grpc_handle.address
        ) as client:
            assert await client.is_server_live()
            assert await client.is_server_ready()
            assert await client.is_model_ready("simple")
            meta = await client.get_model_metadata("simple")
            assert meta.name == "simple"
            in0, in1, inputs = _inputs()
            result = await client.infer("simple", inputs)
            np.testing.assert_array_equal(result.as_numpy("OUTPUT0"),
                                          in0 + in1)
            with pytest.raises(InferenceServerException):
                await client.get_model_metadata("ghost")

    asyncio.run(run())


def test_grpc_aio_full_endpoint_surface(servers, tmp_path):
    """The aio client's tail endpoints match the sync client: trace +
    log settings, statistics, repository index, model control, shm
    status verbs (parity: reference grpc/aio/__init__.py:50 mirrors
    the full method set)."""
    grpc_handle, _ = servers

    async def run():
        async with grpcclient_aio.InferenceServerClient(
            grpc_handle.address
        ) as client:
            # statistics + repository control
            stats = await client.get_inference_statistics("simple")
            assert stats.model_stats[0].name == "simple"
            index = await client.get_model_repository_index()
            assert any(m.name == "simple" for m in index.models)
            await client.load_model("add_sub_fp32")
            assert await client.is_model_ready("add_sub_fp32")
            await client.unload_model("add_sub_fp32")
            assert not await client.is_model_ready("add_sub_fp32")
            # trace settings round trip
            trace_file = str(tmp_path / "aio_trace.jsonl")
            updated = await client.update_trace_settings(
                "simple", {"trace_level": ["TIMESTAMPS"],
                           "trace_file": trace_file, "trace_rate": 1})
            assert updated.settings["trace_file"].value[0] == trace_file
            fetched = await client.get_trace_settings("simple")
            assert fetched.settings["trace_level"].value[0] == "TIMESTAMPS"
            await client.update_trace_settings(
                "simple", {"trace_level": ["OFF"]})
            # log settings round trip
            logs = await client.update_log_settings({"log_verbose_level": 1})
            assert logs.settings["log_verbose_level"].uint32_param == 1
            logs = await client.get_log_settings()
            assert "log_verbose_level" in logs.settings
            # shm status verbs (empty is fine — the verb must answer)
            status = await client.get_system_shared_memory_status()
            assert status is not None
            tpu_status = await client.get_tpu_shared_memory_status()
            assert tpu_status is not None

    asyncio.run(run())


def test_grpc_aio_get_trace_settings_is_pure_read(servers, tmp_path):
    """get_trace_settings must not write: after an update, repeated
    gets return identical settings — a get implemented as an
    empty-settings update could clear or overwrite state on server
    implementations that treat a present map as a write (parity:
    reference grpc/aio get methods issue get RPCs)."""
    grpc_handle, _ = servers

    async def run():
        async with grpcclient_aio.InferenceServerClient(
            grpc_handle.address
        ) as client:
            trace_file = str(tmp_path / "pure_read_trace.jsonl")
            await client.update_trace_settings(
                "simple", {"trace_level": ["TIMESTAMPS"],
                           "trace_file": trace_file, "trace_rate": 7})
            first = await client.get_trace_settings("simple")
            second = await client.get_trace_settings("simple")
            assert first.settings["trace_rate"].value[0] == "7"
            assert first.settings["trace_file"].value[0] == trace_file
            # get-without-write: the read changed nothing
            assert first.settings == second.settings
            logs_first = await client.get_log_settings()
            logs_second = await client.get_log_settings()
            assert logs_first.settings == logs_second.settings
            # A get on a model with no model-specific settings must not
            # snapshot one: a later GLOBAL update still applies to it.
            globals_before = await client.get_trace_settings("")
            await client.get_trace_settings("add_sub_fp32")
            await client.update_trace_settings("", {"trace_rate": 13})
            after = await client.get_trace_settings("add_sub_fp32")
            assert after.settings["trace_rate"].value[0] == "13"
            old_rate = list(
                globals_before.settings["trace_rate"].value) or ["1"]
            await client.update_trace_settings("", {"trace_rate": old_rate})
            await client.update_trace_settings(
                "simple", {"trace_level": ["OFF"]})

    asyncio.run(run())


def test_http_aio_full_endpoint_surface(servers, tmp_path):
    """http.aio's tail endpoints: trace/log settings + statistics +
    model control reach the sync client's surface."""
    _, http_runner = servers

    async def run():
        url = "127.0.0.1:%d" % http_runner.port
        async with httpclient_aio.InferenceServerClient(url) as client:
            stats = await client.get_inference_statistics("simple")
            assert stats["model_stats"][0]["name"] == "simple"
            await client.load_model("add_sub_fp32")
            assert await client.is_model_ready("add_sub_fp32")
            await client.unload_model("add_sub_fp32")
            trace_file = str(tmp_path / "aio_http_trace.jsonl")
            updated = await client.update_trace_settings(
                "simple", {"trace_level": ["TIMESTAMPS"],
                           "trace_file": trace_file})
            assert updated["trace_file"] in (trace_file, [trace_file])
            fetched = await client.get_trace_settings("simple")
            assert fetched["trace_level"] in ("TIMESTAMPS", ["TIMESTAMPS"])
            await client.update_trace_settings(
                "simple", {"trace_level": ["OFF"]})
            logs = await client.update_log_settings(
                {"log_verbose_level": 2})
            assert logs["log_verbose_level"] == 2
            logs = await client.get_log_settings()
            assert "log_verbose_level" in logs

    asyncio.run(run())


def test_grpc_aio_concurrent_infer(servers):
    grpc_handle, _ = servers

    async def run():
        async with grpcclient_aio.InferenceServerClient(
            grpc_handle.address
        ) as client:
            in0, in1, inputs = _inputs()
            results = await asyncio.gather(
                *[client.infer("simple", inputs) for _ in range(16)]
            )
            for result in results:
                np.testing.assert_array_equal(result.as_numpy("OUTPUT1"),
                                              in0 - in1)

    asyncio.run(run())


def test_grpc_aio_stream(servers):
    grpc_handle, _ = servers

    async def run():
        async with grpcclient_aio.InferenceServerClient(
            grpc_handle.address
        ) as client:
            in0, in1, inputs = _inputs()

            async def request_iter():
                for i in range(3):
                    yield {"model_name": "simple", "inputs": inputs,
                           "request_id": str(i)}

            seen = []
            async for result, error in client.stream_infer(request_iter()):
                assert error is None
                seen.append(result.get_response().id)
                np.testing.assert_array_equal(result.as_numpy("OUTPUT0"),
                                              in0 + in1)
            assert seen == ["0", "1", "2"]

    asyncio.run(run())


def test_http_aio_basic(servers):
    _, http_runner = servers

    async def run():
        async with httpclient_aio.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port
        ) as client:
            assert await client.is_server_live()
            assert await client.is_model_ready("simple")
            meta = await client.get_server_metadata()
            assert meta["name"] == "client_tpu_server"
            in0, in1, inputs = _inputs()
            result = await client.infer("simple", inputs, request_id="aio")
            assert result.get_response()["id"] == "aio"
            np.testing.assert_array_equal(result.as_numpy("OUTPUT0"),
                                          in0 + in1)
            stats = await client.get_inference_statistics("simple")
            assert stats["model_stats"][0]["name"] == "simple"
            with pytest.raises(InferenceServerException):
                await client.infer("ghost", inputs)

    asyncio.run(run())


def test_http_aio_concurrent(servers):
    _, http_runner = servers

    async def run():
        async with httpclient_aio.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port
        ) as client:
            in0, in1, inputs = _inputs()
            results = await asyncio.gather(
                *[client.infer("simple", inputs) for _ in range(16)]
            )
            for result in results:
                np.testing.assert_array_equal(result.as_numpy("OUTPUT0"),
                                              in0 + in1)

    asyncio.run(run())
