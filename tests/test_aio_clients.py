"""asyncio client tests (grpc.aio + http.aio) against live servers."""

import asyncio

import numpy as np
import pytest

import client_tpu.grpc.aio as grpcclient_aio
import client_tpu.http.aio as httpclient_aio
from client_tpu._infer_common import InferInput
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.http_server import start_http_server_thread
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def servers():
    core = build_core(["simple"])
    grpc_handle = start_grpc_server(core=core)
    http_runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield grpc_handle, http_runner
    http_runner.stop()
    grpc_handle.stop()


def _inputs():
    in0 = np.arange(16, dtype=np.int32)
    in1 = np.ones(16, dtype=np.int32)
    inputs = [
        InferInput("INPUT0", [16], "INT32"),
        InferInput("INPUT1", [16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(in0)
    inputs[1].set_data_from_numpy(in1)
    return in0, in1, inputs


def test_grpc_aio_basic(servers):
    grpc_handle, _ = servers

    async def run():
        async with grpcclient_aio.InferenceServerClient(
            grpc_handle.address
        ) as client:
            assert await client.is_server_live()
            assert await client.is_server_ready()
            assert await client.is_model_ready("simple")
            meta = await client.get_model_metadata("simple")
            assert meta.name == "simple"
            in0, in1, inputs = _inputs()
            result = await client.infer("simple", inputs)
            np.testing.assert_array_equal(result.as_numpy("OUTPUT0"),
                                          in0 + in1)
            with pytest.raises(InferenceServerException):
                await client.get_model_metadata("ghost")

    asyncio.run(run())


def test_grpc_aio_concurrent_infer(servers):
    grpc_handle, _ = servers

    async def run():
        async with grpcclient_aio.InferenceServerClient(
            grpc_handle.address
        ) as client:
            in0, in1, inputs = _inputs()
            results = await asyncio.gather(
                *[client.infer("simple", inputs) for _ in range(16)]
            )
            for result in results:
                np.testing.assert_array_equal(result.as_numpy("OUTPUT1"),
                                              in0 - in1)

    asyncio.run(run())


def test_grpc_aio_stream(servers):
    grpc_handle, _ = servers

    async def run():
        async with grpcclient_aio.InferenceServerClient(
            grpc_handle.address
        ) as client:
            in0, in1, inputs = _inputs()

            async def request_iter():
                for i in range(3):
                    yield {"model_name": "simple", "inputs": inputs,
                           "request_id": str(i)}

            seen = []
            async for result, error in client.stream_infer(request_iter()):
                assert error is None
                seen.append(result.get_response().id)
                np.testing.assert_array_equal(result.as_numpy("OUTPUT0"),
                                              in0 + in1)
            assert seen == ["0", "1", "2"]

    asyncio.run(run())


def test_http_aio_basic(servers):
    _, http_runner = servers

    async def run():
        async with httpclient_aio.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port
        ) as client:
            assert await client.is_server_live()
            assert await client.is_model_ready("simple")
            meta = await client.get_server_metadata()
            assert meta["name"] == "client_tpu_server"
            in0, in1, inputs = _inputs()
            result = await client.infer("simple", inputs, request_id="aio")
            assert result.get_response()["id"] == "aio"
            np.testing.assert_array_equal(result.as_numpy("OUTPUT0"),
                                          in0 + in1)
            stats = await client.get_inference_statistics("simple")
            assert stats["model_stats"][0]["name"] == "simple"
            with pytest.raises(InferenceServerException):
                await client.infer("ghost", inputs)

    asyncio.run(run())


def test_http_aio_concurrent(servers):
    _, http_runner = servers

    async def run():
        async with httpclient_aio.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port
        ) as client:
            in0, in1, inputs = _inputs()
            results = await asyncio.gather(
                *[client.infer("simple", inputs) for _ in range(16)]
            )
            for result in results:
                np.testing.assert_array_equal(result.as_numpy("OUTPUT0"),
                                              in0 + in1)

    asyncio.run(run())
