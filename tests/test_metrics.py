"""Prometheus metrics: server /metrics endpoint, perf-side scraper
(parity: MetricsManager metrics_manager.h:56-82 with TPU HBM gauges in
place of DCGM GPU gauges), and the CustomLoadManager intervals file."""

import urllib.request

import numpy as np
import pytest

import client_tpu.http as httpclient
from client_tpu.perf.load_manager import CustomLoadManager
from client_tpu.perf.metrics_manager import (
    MetricsManager,
    parse_prometheus,
    summarize_metrics,
)
from client_tpu.server.app import build_core
from client_tpu.server.http_server import start_http_server_thread


@pytest.fixture(scope="module")
def simple_core():
    return build_core(["simple"])


@pytest.fixture(scope="module")
def http_server(simple_core):
    runner = start_http_server_thread(simple_core, host="127.0.0.1", port=0)
    runner.address = "127.0.0.1:%d" % runner.port
    # drive one inference so the counter families are populated
    with httpclient.InferenceServerClient(runner.address) as c:
        inputs = [httpclient.InferInput("INPUT0", [16], "INT32"),
                  httpclient.InferInput("INPUT1", [16], "INT32")]
        inputs[0].set_data_from_numpy(np.arange(16, dtype=np.int32))
        inputs[1].set_data_from_numpy(np.ones(16, dtype=np.int32))
        c.infer("simple", inputs)
    yield runner
    runner.stop()

SAMPLE = """\
# HELP tpu_hbm_used_bytes Accelerator HBM bytes in use
# TYPE tpu_hbm_used_bytes gauge
tpu_hbm_used_bytes{tpu_uuid="TPU-0"} 1048576
tpu_hbm_used_bytes{tpu_uuid="TPU-1"} 2097152
# HELP tpu_hbm_total_bytes Accelerator HBM capacity in bytes
# TYPE tpu_hbm_total_bytes gauge
tpu_hbm_total_bytes{tpu_uuid="TPU-0"} 17179869184
tpu_hbm_utilization{tpu_uuid="TPU-0"} 0.000061
nv_inference_request_success{model="simple",version="1"} 42
"""


def test_parse_prometheus():
    m = parse_prometheus(SAMPLE)
    assert m.hbm_used_bytes == {"TPU-0": 1048576.0, "TPU-1": 2097152.0}
    assert m.hbm_total_bytes == {"TPU-0": 17179869184.0}
    assert m.hbm_utilization["TPU-0"] == pytest.approx(0.000061)


def test_summarize_metrics():
    snaps = [parse_prometheus(SAMPLE), parse_prometheus(SAMPLE)]
    summary = summarize_metrics(snaps)
    # per-snapshot device average of used bytes: (1 MiB + 2 MiB) / 2
    assert summary["hbm_used_bytes"]["avg"] == pytest.approx(1572864.0)
    assert summary["hbm_used_bytes"]["max"] == pytest.approx(1572864.0)


def test_core_metrics_text(simple_core, http_server):
    text = simple_core.metrics_text()
    assert "nv_inference_request_success" in text
    m = parse_prometheus(text)  # parses cleanly even with no gauges
    assert isinstance(m.hbm_used_bytes, dict)


def test_http_metrics_endpoint(http_server):
    url = "http://%s/metrics" % http_server.address
    with urllib.request.urlopen(url, timeout=5) as resp:
        body = resp.read().decode()
    assert resp.status == 200
    assert "# TYPE" in body or body.strip() == ""


def test_metrics_manager_scrape(http_server):
    mm = MetricsManager(http_server.address, metrics_interval_ms=20)
    mm.check_reachable()
    mm.start()
    import time

    time.sleep(0.2)
    mm.stop()
    snaps = mm.get_and_reset()
    assert snaps, "expected at least one scrape"
    assert mm.get_and_reset() == []  # reset drained the buffer


def test_metrics_manager_unreachable():
    mm = MetricsManager("127.0.0.1:59999", metrics_interval_ms=20,
                        timeout_s=0.2)
    with pytest.raises(Exception):
        mm.check_reachable()


def test_custom_intervals_file(tmp_path):
    path = tmp_path / "intervals.txt"
    path.write_text("1000\n2000\n1500\n")
    intervals = CustomLoadManager.read_intervals_file(str(path))
    assert intervals == [0.001, 0.002, 0.0015]


def test_custom_intervals_empty_file(tmp_path):
    path = tmp_path / "empty.txt"
    path.write_text("\n")
    with pytest.raises(ValueError):
        CustomLoadManager.read_intervals_file(str(path))
