"""Server-side dynamic batching tests: concurrent requests fuse along
the batch dimension into fewer model executions (the TPU-first
equivalent of Triton's dynamic batcher)."""

import threading

import numpy as np
import pytest

from client_tpu.server.batcher import DynamicBatcher, wants_dynamic_batching
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.utils import InferenceServerException


class CountingModel(ServedModel):
    """Echo model that counts executions and records batch sizes."""

    max_batch_size = 8
    dynamic_batching = True

    def __init__(self, delay_s: float = 0.0):
        super().__init__()
        self.name = "counting"
        self.inputs = [TensorSpec("IN", "FP32", [4])]
        self.outputs = [TensorSpec("OUT", "FP32", [4])]
        self.executions = []
        self.gate = threading.Event()
        self.gate.set()
        self._delay = delay_s

    def infer(self, inputs, parameters=None):
        self.gate.wait()
        if self._delay:
            import time

            time.sleep(self._delay)
        array = np.asarray(inputs["IN"])
        self.executions.append(array.shape[0])
        return {"OUT": array * 2.0}


def test_wants_dynamic_batching():
    assert wants_dynamic_batching(CountingModel())

    class NoBatch(ServedModel):
        max_batch_size = 8

    assert not wants_dynamic_batching(NoBatch())

    class Decoupled(CountingModel):
        decoupled = True

    assert not wants_dynamic_batching(Decoupled())


def test_fuses_concurrent_requests():
    model = CountingModel()
    model.gate.clear()  # hold the first execution so requests pile up
    batcher = DynamicBatcher(model, max_queue_delay_us=200000)
    results = [None] * 6
    errors = []

    def one(i):
        try:
            data = np.full((1, 4), float(i), dtype=np.float32)
            outputs, queue_ns, _ = batcher.infer({"IN": data}, {}, 1)
            results[i] = (outputs["OUT"], queue_ns)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.1)  # let every request enqueue
    model.gate.set()
    for t in threads:
        t.join(timeout=10)
    batcher.stop()

    assert not errors
    # Far fewer executions than requests; fused batches may be padded
    # up to a stable compile shape but never above max batch.
    assert len(model.executions) < 6
    assert sum(model.executions) >= 6
    assert max(model.executions) <= model.max_batch_size
    for i, (out, queue_ns) in enumerate(results):
        assert out.shape == (1, 4)
        np.testing.assert_array_equal(out, np.full((1, 4), i * 2.0))
        assert queue_ns >= 0


def test_shape_mismatch_not_fused():
    model = CountingModel()

    class VarModel(CountingModel):
        def __init__(self):
            super().__init__()
            self.inputs = [TensorSpec("IN", "FP32", [-1])]

    model = VarModel()
    model.gate.clear()
    batcher = DynamicBatcher(model, max_queue_delay_us=100000)
    done = []

    def one(width):
        data = np.zeros((1, width), dtype=np.float32)
        outputs, _, _ = batcher.infer({"IN": data}, {}, 1)
        done.append(outputs["OUT"].shape)

    threads = [threading.Thread(target=one, args=(w,)) for w in (4, 4, 8)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.1)
    model.gate.set()
    for t in threads:
        t.join(timeout=10)
    batcher.stop()
    # Two width-4 requests fused (padded to 2); the width-8 request
    # ran alone (padded to its own compile shape).
    assert len(model.executions) == 2


def test_error_propagates_to_every_request():
    class FailingModel(CountingModel):
        def infer(self, inputs, parameters=None):
            super().infer(inputs, parameters)
            raise InferenceServerException("boom", status="INTERNAL")

    model = FailingModel()
    model.gate.clear()
    batcher = DynamicBatcher(model, max_queue_delay_us=100000)
    errors = []

    def one():
        try:
            batcher.infer(
                {"IN": np.zeros((1, 4), dtype=np.float32)}, {}, 1)
        except InferenceServerException as e:
            errors.append(str(e))

    threads = [threading.Thread(target=one) for _ in range(3)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.05)
    model.gate.set()
    for t in threads:
        t.join(timeout=10)
    batcher.stop()
    assert len(errors) == 3


def test_device_chunks_fuse_on_device():
    """Arena-resolved inputs are jax.Arrays; fusing them must run as
    device ops — a numpy concat would drag every chunk back to host
    (the round-2 12-infer/s regression). The model asserts its fused
    input is still a device array (fusion runs on the gather thread,
    so a thread-local transfer guard here could not see it)."""
    import jax.numpy as jnp

    class DeviceModel(CountingModel):
        def infer(self, inputs, parameters=None):
            self.gate.wait()  # keep the pile-up choreography working
            array = inputs["IN"]
            assert not isinstance(array, np.ndarray), \
                "fused input fell back to host"
            self.executions.append(array.shape[0])
            return {"OUT": array * 2.0}

    model = DeviceModel()
    model.gate.clear()
    batcher = DynamicBatcher(model, max_queue_delay_us=200000)
    results = [None] * 4
    errors = []

    def one(i):
        try:
            data = jnp.full((2, 4), float(i), dtype=jnp.float32)
            outputs, _, _ = batcher.infer({"IN": data}, {}, 2)
            results[i] = outputs["OUT"]
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.1)
    model.gate.set()
    for t in threads:
        t.join(timeout=10)
    batcher.stop()

    assert not errors, errors[0]
    assert len(model.executions) < 4  # requests actually fused
    for i, out in enumerate(results):
        np.testing.assert_array_equal(
            np.asarray(out), np.full((2, 4), i * 2.0, dtype=np.float32))


def test_device_chunks_fuse_with_padding_on_device():
    """Padding to the preferred compile shape must also stay on device."""
    import jax
    import jax.numpy as jnp
    from client_tpu.server.batcher import _fuse_chunks

    chunks = [jnp.ones((2, 4)), jnp.zeros((1, 4))]
    # d2h is the defeat we guard against; tiny h2d offset scalars are
    # expected (dynamic_update_slice start indices ride as arguments).
    with jax.transfer_guard_device_to_host("disallow"):
        fused = _fuse_chunks(chunks, target=8, total=3)
    assert fused.shape == (8, 4)
    host = np.asarray(fused)
    np.testing.assert_array_equal(host[:2], 1.0)
    np.testing.assert_array_equal(host[2:], 0.0)  # pad rows stay zero


def test_e2e_server_fuses_and_reports_queue_time():
    """Concurrent gRPC clients against a dynamic-batching model: the
    server reports execution_count < inference_count and non-zero
    cumulative queue time."""
    import client_tpu.grpc as grpcclient
    from client_tpu.server.app import build_core, start_grpc_server

    core = build_core([])
    model = CountingModel(delay_s=0.005)
    core.repository.add_model(model)
    handle = start_grpc_server(core=core)
    try:
        def worker():
            with grpcclient.InferenceServerClient(handle.address) as client:
                inputs = [grpcclient.InferInput("IN", [1, 4], "FP32")]
                inputs[0].set_data_from_numpy(
                    np.ones((1, 4), dtype=np.float32))
                for _ in range(10):
                    result = client.infer("counting", inputs)
                    np.testing.assert_array_equal(
                        result.as_numpy("OUT"),
                        np.full((1, 4), 2.0, dtype=np.float32))

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)

        stats = core.model_statistics("counting").model_stats[0]
        assert stats.inference_count == 40
        assert stats.execution_count < 40, (
            "no fusing happened (executions=%d)" % stats.execution_count
        )
        assert stats.inference_stats.queue.ns > 0
    finally:
        handle.stop()
