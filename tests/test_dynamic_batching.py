"""Server-side dynamic batching tests: concurrent requests fuse along
the batch dimension into fewer model executions (the TPU-first
equivalent of Triton's dynamic batcher)."""

import threading

import numpy as np
import pytest

from client_tpu.server.batcher import DynamicBatcher, wants_dynamic_batching
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.utils import InferenceServerException


class CountingModel(ServedModel):
    """Echo model that counts executions and records batch sizes."""

    max_batch_size = 8
    dynamic_batching = True

    def __init__(self, delay_s: float = 0.0):
        super().__init__()
        self.name = "counting"
        self.inputs = [TensorSpec("IN", "FP32", [4])]
        self.outputs = [TensorSpec("OUT", "FP32", [4])]
        self.executions = []
        self.gate = threading.Event()
        self.gate.set()
        self._delay = delay_s

    def infer(self, inputs, parameters=None):
        self.gate.wait()
        if self._delay:
            import time

            time.sleep(self._delay)
        array = np.asarray(inputs["IN"])
        self.executions.append(array.shape[0])
        return {"OUT": array * 2.0}


def test_wants_dynamic_batching():
    assert wants_dynamic_batching(CountingModel())

    class NoBatch(ServedModel):
        max_batch_size = 8

    assert not wants_dynamic_batching(NoBatch())

    class Decoupled(CountingModel):
        decoupled = True

    assert not wants_dynamic_batching(Decoupled())


def test_fuses_concurrent_requests():
    model = CountingModel()
    model.gate.clear()  # hold the first execution so requests pile up
    batcher = DynamicBatcher(model, max_queue_delay_us=200000)
    results = [None] * 6
    errors = []

    def one(i):
        try:
            data = np.full((1, 4), float(i), dtype=np.float32)
            outputs, queue_ns, _ = batcher.infer({"IN": data}, {}, 1)
            results[i] = (outputs["OUT"], queue_ns)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.1)  # let every request enqueue
    model.gate.set()
    for t in threads:
        t.join(timeout=10)
    batcher.stop()

    assert not errors
    # Far fewer executions than requests; fused batches may be padded
    # up to a stable compile shape but never above max batch.
    assert len(model.executions) < 6
    assert sum(model.executions) >= 6
    assert max(model.executions) <= model.max_batch_size
    for i, (out, queue_ns) in enumerate(results):
        assert out.shape == (1, 4)
        np.testing.assert_array_equal(out, np.full((1, 4), i * 2.0))
        assert queue_ns >= 0


def test_shape_mismatch_not_fused():
    model = CountingModel()

    class VarModel(CountingModel):
        def __init__(self):
            super().__init__()
            self.inputs = [TensorSpec("IN", "FP32", [-1])]

    model = VarModel()
    model.gate.clear()
    batcher = DynamicBatcher(model, max_queue_delay_us=100000)
    done = []

    def one(width):
        data = np.zeros((1, width), dtype=np.float32)
        outputs, _, _ = batcher.infer({"IN": data}, {}, 1)
        done.append(outputs["OUT"].shape)

    threads = [threading.Thread(target=one, args=(w,)) for w in (4, 4, 8)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.1)
    model.gate.set()
    for t in threads:
        t.join(timeout=10)
    batcher.stop()
    # Two width-4 requests fused (padded to 2); the width-8 request
    # ran alone (padded to its own compile shape).
    assert len(model.executions) == 2


def test_error_propagates_to_every_request():
    class FailingModel(CountingModel):
        def infer(self, inputs, parameters=None):
            super().infer(inputs, parameters)
            raise InferenceServerException("boom", status="INTERNAL")

    model = FailingModel()
    model.gate.clear()
    batcher = DynamicBatcher(model, max_queue_delay_us=100000)
    errors = []

    def one():
        try:
            batcher.infer(
                {"IN": np.zeros((1, 4), dtype=np.float32)}, {}, 1)
        except InferenceServerException as e:
            errors.append(str(e))

    threads = [threading.Thread(target=one) for _ in range(3)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.05)
    model.gate.set()
    for t in threads:
        t.join(timeout=10)
    batcher.stop()
    assert len(errors) == 3


def test_device_chunks_fuse_on_device():
    """Arena-resolved inputs are jax.Arrays; fusing them must run as
    device ops — a numpy concat would drag every chunk back to host
    (the round-2 12-infer/s regression). The model asserts its fused
    input is still a device array (fusion runs on the gather thread,
    so a thread-local transfer guard here could not see it)."""
    import jax.numpy as jnp

    class DeviceModel(CountingModel):
        def infer(self, inputs, parameters=None):
            self.gate.wait()  # keep the pile-up choreography working
            array = inputs["IN"]
            assert not isinstance(array, np.ndarray), \
                "fused input fell back to host"
            self.executions.append(array.shape[0])
            return {"OUT": array * 2.0}

    model = DeviceModel()
    model.gate.clear()
    batcher = DynamicBatcher(model, max_queue_delay_us=200000)
    results = [None] * 4
    errors = []

    def one(i):
        try:
            data = jnp.full((2, 4), float(i), dtype=jnp.float32)
            outputs, _, _ = batcher.infer({"IN": data}, {}, 2)
            results[i] = outputs["OUT"]
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.1)
    model.gate.set()
    for t in threads:
        t.join(timeout=10)
    batcher.stop()

    assert not errors, errors[0]
    assert len(model.executions) < 4  # requests actually fused
    for i, out in enumerate(results):
        np.testing.assert_array_equal(
            np.asarray(out), np.full((2, 4), i * 2.0, dtype=np.float32))


def test_device_chunks_fuse_with_padding_on_device():
    """Padding to the preferred compile shape must also stay on device."""
    import jax
    import jax.numpy as jnp
    from client_tpu.server.batcher import _fuse_chunks

    chunks = [jnp.ones((2, 4)), jnp.zeros((1, 4))]
    # d2h is the defeat we guard against; tiny h2d offset scalars are
    # expected (dynamic_update_slice start indices ride as arguments).
    with jax.transfer_guard_device_to_host("disallow"):
        fused = _fuse_chunks(chunks, target=8, total=3)
    assert fused.shape == (8, 4)
    host = np.asarray(fused)
    np.testing.assert_array_equal(host[:2], 1.0)
    np.testing.assert_array_equal(host[2:], 0.0)  # pad rows stay zero


def test_e2e_server_fuses_and_reports_queue_time():
    """Concurrent gRPC clients against a dynamic-batching model: the
    server reports execution_count < inference_count and non-zero
    cumulative queue time."""
    import client_tpu.grpc as grpcclient
    from client_tpu.server.app import build_core, start_grpc_server

    core = build_core([])
    model = CountingModel(delay_s=0.005)
    core.repository.add_model(model)
    handle = start_grpc_server(core=core)
    try:
        def worker():
            with grpcclient.InferenceServerClient(handle.address) as client:
                inputs = [grpcclient.InferInput("IN", [1, 4], "FP32")]
                inputs[0].set_data_from_numpy(
                    np.ones((1, 4), dtype=np.float32))
                for _ in range(10):
                    result = client.infer("counting", inputs)
                    np.testing.assert_array_equal(
                        result.as_numpy("OUT"),
                        np.full((1, 4), 2.0, dtype=np.float32))

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)

        stats = core.model_statistics("counting").model_stats[0]
        assert stats.inference_count == 40
        assert stats.execution_count < 40, (
            "no fusing happened (executions=%d)" % stats.execution_count
        )
        assert stats.inference_stats.queue.ns > 0
    finally:
        handle.stop()


# -- pipelined batcher -----------------------------------------------------


def test_per_shape_bucket_queues_fuse_interleaved_shapes():
    """Interleaved arrivals of two shapes must not fragment either
    shape's bucket: each shape accumulates in its own queue and fuses
    into one execution."""

    class VarModel(CountingModel):
        def __init__(self):
            super().__init__()
            self.inputs = [TensorSpec("IN", "FP32", [-1])]

    model = VarModel()
    model.gate.clear()
    batcher = DynamicBatcher(model, max_queue_delay_us=150000)
    errors = []

    def one(width, value):
        try:
            data = np.full((1, width), value, dtype=np.float32)
            outputs, _, _ = batcher.infer({"IN": data}, {}, 1)
            np.testing.assert_array_equal(outputs["OUT"], data * 2.0)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    # a,b,a,b,a,b interleaving
    widths = [4, 8, 4, 8, 4, 8]
    threads = []
    for i, width in enumerate(widths):
        t = threading.Thread(target=one, args=(width, float(i)))
        t.start()
        threads.append(t)
        import time

        time.sleep(0.01)
    time.sleep(0.1)
    model.gate.set()
    for t in threads:
        t.join(timeout=10)
    batcher.stop()
    assert not errors, errors[0]
    # one fused execution per shape, not one per shape *change*
    assert len(model.executions) == 2, model.executions


def test_adaptive_delay_bounds():
    """Deterministic bound checks (integer-us EMAs only)."""
    model = CountingModel()
    batcher = DynamicBatcher(
        model, max_queue_delay_us=1000, preferred_batch_sizes=[8],
        delay_min_us=500, delay_max_us=20000)
    try:
        def delay_us_for(ema_us):
            with batcher._cv:
                batcher._ia_ema_ns = ema_us * 1000
                return batcher._adaptive_delay_ns() / 1000

        assert delay_us_for(100) == 700      # 100us * (8-1)
        assert delay_us_for(1000) == 7000    # proportional
        assert delay_us_for(1) == 500        # floored at delay_min
        assert delay_us_for(5000) == 20000   # capped at delay_max
        assert delay_us_for(15000) == 500    # sparse -> floor
    finally:
        batcher.stop()
    # no preferred sizes -> no adaptation, configured delay as-is
    plain = DynamicBatcher(CountingModel(), max_queue_delay_us=1000)
    try:
        with plain._cv:
            plain._ia_ema_ns = 100 * 1000
            assert plain._adaptive_delay_ns() == 1000 * 1000
    finally:
        plain.stop()


def test_stalled_stream_dispatches_partial_bucket():
    """A bounded closed loop stops producing once every client is
    queued; the idle-gap cutoff must dispatch the partial bucket
    instead of waiting out the adaptive window sized for preferred-64
    traffic."""
    import time

    class WideModel(CountingModel):
        max_batch_size = 64
        preferred_batch_sizes = [64]

    model = WideModel()
    batcher = DynamicBatcher(model, max_queue_delay_us=5000,
                             delay_max_us=500000)
    results, errors = [], []

    def one(i):
        try:
            data = np.full((1, 4), float(i), dtype=np.float32)
            outputs, _, _ = batcher.infer({"IN": data}, {}, 1)
            results.append(np.asarray(outputs["OUT"]))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    t0 = time.monotonic()
    threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
        time.sleep(0.001)  # a live EMA (~1ms), then the stream stalls
    for t in threads:
        t.join(timeout=30)
    elapsed = time.monotonic() - t0
    batcher.stop()
    assert not errors, errors[0]
    assert len(results) == 4
    # adaptive target would be ~1ms * 63 = 63ms; the idle-gap cutoff
    # (~4-5ms after the last arrival) must beat it by a wide margin
    assert elapsed < 0.05, "stalled stream waited out the full window"


class _SlowFetchArray:
    """Array-like whose host materialization (np.asarray) takes
    `delay_s` — a stand-in for the device->host relay fetch."""

    def __init__(self, data, delay_s):
        self._data = data
        self._delay_s = delay_s
        self.shape = data.shape
        self.dtype = data.dtype

    def __array__(self, dtype=None, copy=None):
        import time

        time.sleep(self._delay_s)
        return self._data


def test_pipeline_overlaps_compute_with_fetch():
    """>=2 fused batches genuinely in flight: batch N+1's device
    compute runs while batch N's output fetch is still in progress,
    and the tracker records the overlap."""
    import time

    class SlowFetchModel(CountingModel):
        def infer(self, inputs, parameters=None):
            array = np.asarray(inputs["IN"])
            self.executions.append(array.shape[0])
            time.sleep(0.05)  # device compute
            return {"OUT": _SlowFetchArray(array * 2.0, 0.25)}

    model = SlowFetchModel()
    batcher = DynamicBatcher(model, max_queue_delay_us=20000,
                             pipeline_depth=4)
    errors, results = [], {}

    def one(i):
        try:
            data = np.full((1, 4), float(i), dtype=np.float32)
            outputs, _, _ = batcher.infer({"IN": data}, {}, 1)
            results[i] = np.asarray(outputs["OUT"])
        except Exception as e:  # pragma: no cover
            errors.append(e)

    # Two waves far enough apart to land in different buckets, close
    # enough that wave 1's fetch (250 ms) is still in flight when wave
    # 2's compute dispatches.
    threads = []
    for i in (0, 1):
        t = threading.Thread(target=one, args=(i,))
        t.start()
        threads.append(t)
    time.sleep(0.12)  # wave 1 dispatched (compute 50ms done, fetching)
    for i in (2, 3):
        t = threading.Thread(target=one, args=(i,))
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=20)
    snap = batcher.stats_snapshot()
    batcher.stop()
    assert not errors, errors[0]
    assert len(model.executions) == 2, model.executions
    for i in range(4):
        np.testing.assert_array_equal(
            results[i], np.full((1, 4), i * 2.0, dtype=np.float32))
    assert snap["fetch_ns"] > 0
    # wave 2's 50ms compute must have landed inside wave 1's 250ms fetch
    assert snap["overlap_ns"] > 0, snap
    assert snap["overlap_ratio"] > 0.0


def test_error_in_batch_does_not_poison_next_batch():
    """A failing fused batch propagates its error to exactly its own
    requests; the next batch through the pipeline is unaffected."""

    class SelectivelyFailingModel(CountingModel):
        def infer(self, inputs, parameters=None):
            self.gate.wait()
            array = np.asarray(inputs["IN"])
            self.executions.append(array.shape[0])
            if float(array[0, 0]) < 0:
                raise InferenceServerException("boom", status="INTERNAL")
            return {"OUT": array * 2.0}

    model = SelectivelyFailingModel()
    model.inputs = [TensorSpec("IN", "FP32", [-1])]
    model.gate.clear()
    batcher = DynamicBatcher(model, max_queue_delay_us=100000)
    outcomes = {}

    def one(key, width, value):
        data = np.full((1, width), value, dtype=np.float32)
        try:
            outputs, _, _ = batcher.infer({"IN": data}, {}, 1)
            outcomes[key] = np.asarray(outputs["OUT"])
        except InferenceServerException as e:
            outcomes[key] = e

    # widths differ -> two buckets; the width-4 bucket fails
    threads = [
        threading.Thread(target=one, args=("bad0", 4, -1.0)),
        threading.Thread(target=one, args=("bad1", 4, -1.0)),
        threading.Thread(target=one, args=("good0", 8, 3.0)),
        threading.Thread(target=one, args=("good1", 8, 3.0)),
    ]
    for t in threads:
        t.start()
    import time

    time.sleep(0.1)
    model.gate.set()
    for t in threads:
        t.join(timeout=10)
    batcher.stop()
    assert isinstance(outcomes["bad0"], InferenceServerException)
    assert isinstance(outcomes["bad1"], InferenceServerException)
    for key in ("good0", "good1"):
        np.testing.assert_array_equal(
            outcomes[key], np.full((1, 8), 6.0, dtype=np.float32))


def test_drain_on_shutdown_executes_queued_requests():
    """stop() must drain: requests still waiting out their gather
    window execute immediately (deadlines void) instead of being
    dropped or stranded."""
    model = CountingModel()
    # 10s window: without the drain these would still be queued when
    # the test times out below.
    batcher = DynamicBatcher(model, max_queue_delay_us=10_000_000)
    results, errors = [], []

    def one(i):
        try:
            data = np.full((1, 4), float(i), dtype=np.float32)
            outputs, _, _ = batcher.infer({"IN": data}, {}, 1)
            results.append(np.asarray(outputs["OUT"]))
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.1)  # all three queued, none near its 10s deadline
    t0 = time.monotonic()
    batcher.stop()
    for t in threads:
        t.join(timeout=10)
    elapsed = time.monotonic() - t0
    assert not errors, errors[0]
    assert len(results) == 3
    assert elapsed < 5.0, "drain waited out the gather window"
    assert sum(model.executions) >= 3


def test_fetch_pool_sizing_configurable():
    """The fetch pool honours an explicit worker count and otherwise
    sizes itself from the pipeline depth."""
    model = CountingModel()
    b1 = DynamicBatcher(model, fetch_workers=7)
    b2 = DynamicBatcher(model, pipeline_depth=6)
    b3 = DynamicBatcher(model)
    try:
        assert b1._fetch_workers == 7
        assert b2._fetch_workers == 6
        assert b3._fetch_workers == max(2, b3._depth)
    finally:
        b1.stop()
        b2.stop()
        b3.stop()


def test_statistics_expose_histogram_and_pipeline():
    """The server statistics carry the fused-batch-size histogram
    (batch_stats) and the pipeline gauges/overlap (pipeline_stats),
    over both front-end surfaces and /metrics."""
    from client_tpu.server.app import build_core, start_grpc_server
    import client_tpu.grpc as grpcclient

    core = build_core([])
    model = CountingModel(delay_s=0.005)
    core.repository.add_model(model)
    handle = start_grpc_server(core=core)
    try:
        def worker():
            with grpcclient.InferenceServerClient(handle.address) as client:
                inputs = [grpcclient.InferInput("IN", [1, 4], "FP32")]
                inputs[0].set_data_from_numpy(
                    np.ones((1, 4), dtype=np.float32))
                for _ in range(8):
                    client.infer("counting", inputs)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)

        stats = core.model_statistics("counting").model_stats[0]
        hist = {int(r.batch_size): int(r.compute_infer.count)
                for r in stats.batch_stats}
        assert hist, "no fused-batch histogram recorded"
        assert sum(hist.values()) == stats.execution_count
        assert stats.pipeline_stats.queue_delay_us > 0
        assert stats.pipeline_stats.compute_ns > 0

        # gRPC front-end: same proto rides through ModelStatistics
        with grpcclient.InferenceServerClient(handle.address) as client:
            wire = client.get_inference_statistics("counting")
            entry = wire.model_stats[0]
            assert [int(r.batch_size) for r in entry.batch_stats]
            assert entry.pipeline_stats.queue_delay_us > 0

        # Prometheus: histogram + gauges scrape-able
        text = core.metrics_text()
        assert "tpu_batch_fused_total" in text
        assert 'tpu_batch_pending_depth{model="counting"}' in text
        assert 'tpu_batch_overlap_ratio{model="counting"}' in text
    finally:
        handle.stop()


def test_statistics_over_http_endpoint():
    """The HTTP /v2/models/{m}/stats surface carries the new fields."""
    from client_tpu.server.app import build_core
    from client_tpu.server.http_server import start_http_server_thread
    import client_tpu.http as httpclient

    core = build_core([])
    model = CountingModel(delay_s=0.002)
    core.repository.add_model(model)
    server = start_http_server_thread(core, host="127.0.0.1", port=0)
    try:
        address = "127.0.0.1:%d" % server.port

        def worker():
            with httpclient.InferenceServerClient(address) as client:
                inputs = [httpclient.InferInput("IN", [1, 4], "FP32")]
                inputs[0].set_data_from_numpy(
                    np.ones((1, 4), dtype=np.float32))
                for _ in range(6):
                    client.infer("counting", inputs)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)

        with httpclient.InferenceServerClient(address) as client:
            stats = client.get_inference_statistics("counting")
        entry = stats["model_stats"][0]
        assert entry.get("batch_stats"), entry
        pipe = entry.get("pipeline_stats", {})
        assert int(pipe.get("queue_delay_us", 0)) > 0
        assert int(pipe.get("compute_ns", 0)) > 0
    finally:
        server.stop()
        core.shutdown()
