"""TF-Serving gRPC PredictionService backend: the compiled
wire-compatible proto subset, the Python backend, and the native
harness, all against a mock TF-Serving server (parity: the reference's
tensorflow_serving client backend speaks this exact protocol)."""

import pathlib
import subprocess
from concurrent import futures

import numpy as np
import pytest

from client_tpu.protocol import tensorflow_serving_apis_pb2 as tfs

REPO = pathlib.Path(__file__).resolve().parent.parent


class _MockPredictionService:
    """Predict handler: y = x * 2 for every numeric input tensor;
    BYTES inputs are upper-cased. Records request count."""

    def __init__(self):
        self.requests = 0

    def predict(self, request, context):
        self.requests += 1
        typed = request.model_spec.name == "typed_echo"
        response = tfs.PredictResponse()
        response.model_spec.CopyFrom(request.model_spec)
        for name, tensor in request.inputs.items():
            out = response.outputs["out_" + name]
            out.dtype = tensor.dtype
            out.tensor_shape.CopyFrom(tensor.tensor_shape)
            if tensor.dtype == 7:  # DT_STRING
                out.string_val.extend(s.upper() for s in tensor.string_val)
            elif typed:
                # Real TF-Serving answers in TYPED fields
                # (Tensor::AsProtoField), not tensor_content.
                array = np.frombuffer(
                    tensor.tensor_content, dtype=_np_dtype(tensor.dtype))
                out.float_val.extend(float(v) * 2 for v in array)
            else:
                array = np.frombuffer(
                    tensor.tensor_content, dtype=_np_dtype(tensor.dtype))
                out.tensor_content = (array * 2).tobytes()
        return response


def _np_dtype(tf_enum):
    return {1: np.float32, 3: np.int32, 9: np.int64}[tf_enum]


@pytest.fixture(scope="module")
def mock_tfserving():
    import grpc

    service = _MockPredictionService()
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    handler = grpc.method_handlers_generic_handler(
        "tensorflow.serving.PredictionService",
        {"Predict": grpc.unary_unary_rpc_method_handler(
            service.predict,
            request_deserializer=tfs.PredictRequest.FromString,
            response_serializer=tfs.PredictResponse.SerializeToString,
        )},
    )
    server.add_generic_rpc_handlers((handler,))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    yield {"address": "127.0.0.1:%d" % port, "service": service}
    server.stop(grace=None)


def test_python_backend_predict_round_trip(mock_tfserving):
    from client_tpu.perf.client_backend import (
        BackendKind,
        ClientBackendFactory,
    )
    from client_tpu.perf.client_backend import TfServingGrpcBackend

    factory = ClientBackendFactory(
        BackendKind.TFSERVING, url=mock_tfserving["address"])
    backend = factory.create()
    assert isinstance(backend, TfServingGrpcBackend)

    from client_tpu._infer_common import InferInput

    x = InferInput("x", [4], "FP32")
    x.set_data_from_numpy(np.arange(4, dtype=np.float32))
    result = backend.infer("echo", [x])
    np.testing.assert_array_equal(
        result.as_numpy("out_x"), np.arange(4, dtype=np.float32) * 2)
    backend.close()


def test_python_backend_typed_field_outputs(mock_tfserving):
    """Real TF-Serving replies via typed repeated fields; the result
    wrapper must decode those too, not just tensor_content."""
    from client_tpu.perf.client_backend import TfServingGrpcBackend

    backend = TfServingGrpcBackend(mock_tfserving["address"])
    from client_tpu._infer_common import InferInput

    x = InferInput("x", [4], "FP32")
    x.set_data_from_numpy(np.arange(4, dtype=np.float32))
    result = backend.infer("typed_echo", [x])
    np.testing.assert_array_equal(
        result.as_numpy("out_x"), np.arange(4, dtype=np.float32) * 2)
    backend.close()


def test_python_backend_bytes_strings(mock_tfserving):
    from client_tpu.perf.client_backend import TfServingGrpcBackend

    backend = TfServingGrpcBackend(mock_tfserving["address"])
    from client_tpu._infer_common import InferInput

    s = InferInput("s", [2], "BYTES")
    s.set_data_from_numpy(np.array([b"ab", b"cd"], dtype=np.object_))
    result = backend.infer("echo", [s])
    np.testing.assert_array_equal(
        result.as_numpy("out_s"),
        np.array([b"AB", b"CD"], dtype=np.object_))
    backend.close()


def test_python_harness_cli_against_mock(mock_tfserving):
    """Full Python perf run: --service-kind tfserving over gRPC, the
    input declared via the new name:DTYPE:dims --shape form."""
    from client_tpu.perf.cli import run as perf_main

    rc = perf_main([
        "-m", "echo", "-u", mock_tfserving["address"],
        "--service-kind", "tfserving",
        "--shape", "x:FP32:16",
        "--concurrency-range", "2", "-p", "300", "-r", "3", "-s", "90",
    ])
    assert rc == 0


def test_native_harness_against_mock(mock_tfserving):
    binary = REPO / "native" / "build" / "perf_analyzer"
    if not binary.exists():
        pytest.skip("native harness not built")
    before = mock_tfserving["service"].requests
    proc = subprocess.run(
        [str(binary), "-m", "echo", "-u", mock_tfserving["address"],
         "--service-kind", "tfserving",
         "--shape", "x:FP32:16",
         "--concurrency-range", "2", "-p", "300", "-r", "3", "-s", "90"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "throughput" in proc.stdout
    assert "errors" not in proc.stdout, proc.stdout
    assert mock_tfserving["service"].requests > before
