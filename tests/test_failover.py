"""Multi-endpoint robustness: EndpointPool routing/hedging/failover
(unit level), two-server kill-mid-load and latency-spike scenarios over
real HTTP transports, breaker ejection + prober readmission, sequence
stickiness across failover, Retry-After honoring on both transports,
and the graceful-unload drain."""

import threading
import time

import numpy as np
import pytest

from client_tpu import robust
from client_tpu.robust import (
    CircuitBreaker,
    EndpointPool,
    RetryPolicy,
    call_with_retry,
    call_with_retry_pool,
)
from client_tpu.utils import InferenceServerException


@pytest.fixture(autouse=True)
def _reset_counters():
    robust.reset_retry_total()
    yield
    robust.reset_retry_total()


# -- EndpointPool unit level ----------------------------------------------


def test_split_url_forms():
    assert EndpointPool.split_url("a:1") == ["a:1"]
    assert EndpointPool.split_url("a:1, b:2,") == ["a:1", "b:2"]
    assert EndpointPool.split_url(["a:1", "b:2"]) == ["a:1", "b:2"]
    with pytest.raises(ValueError):
        EndpointPool(["a:1", "a:1"])  # duplicates would alias state
    with pytest.raises(ValueError):
        EndpointPool([])


def test_routing_prefers_low_expected_completion():
    pool = EndpointPool(["fast", "slow"], explore_ratio=0.0)
    pool.endpoints["fast"].ewma_latency_s = 0.005
    pool.endpoints["slow"].ewma_latency_s = 0.200
    # idle: the 40x faster endpoint wins even at equal outstanding
    assert pool.pick().url == "fast"
    # the score is (outstanding+1) * ewma: fast stays preferred until
    # its queue is ~40 deep
    with pool._lock:
        pool.endpoints["fast"].outstanding = 10
    assert pool.pick().url == "fast"
    with pool._lock:
        pool.endpoints["fast"].outstanding = 100
    assert pool.pick().url == "slow"


def test_failover_on_retryable_error():
    pool = EndpointPool(["a", "b"], hedge_max_ratio=0.0, explore_ratio=0.0)

    def fn(state, remaining):
        if state.url == "a":
            raise InferenceServerException("down", status="UNAVAILABLE")
        return "ok"

    policy = RetryPolicy(max_attempts=3, initial_backoff_s=0.001)
    for _ in range(6):
        assert call_with_retry_pool(fn, pool, policy) == "ok"
    stats = pool.stats()
    assert stats["failovers"] >= 1
    # after enough consecutive failures endpoint a is ejected
    assert stats["ejections"] == 1
    assert pool.endpoints["a"].breaker.state == CircuitBreaker.OPEN
    # with a ejected, requests route straight to b — no more failovers
    before = pool.stats()["failovers"]
    assert call_with_retry_pool(fn, pool, policy) == "ok"
    assert pool.stats()["failovers"] == before


def test_non_retryable_error_does_not_fail_over():
    pool = EndpointPool(["a", "b"], hedge_max_ratio=0.0, explore_ratio=0.0)
    calls = []

    def bad(state, remaining):
        calls.append(state.url)
        raise InferenceServerException("bad", status="INVALID_ARGUMENT")

    with pytest.raises(InferenceServerException):
        call_with_retry_pool(bad, pool, RetryPolicy(max_attempts=4))
    assert len(calls) == 1


def test_all_endpoints_ejected_fails_fast():
    pool = EndpointPool(
        ["a", "b"],
        breaker_factory=lambda: CircuitBreaker(failure_threshold=1,
                                               reset_timeout_s=60.0),
        hedge_max_ratio=0.0, explore_ratio=0.0)

    def down(state, remaining):
        raise InferenceServerException("down", status="UNAVAILABLE")

    with pytest.raises(InferenceServerException):
        call_with_retry_pool(down, pool,
                             RetryPolicy(max_attempts=4,
                                         initial_backoff_s=0.001))
    assert pool.stats()["ejections"] == 2
    calls = []
    with pytest.raises(InferenceServerException) as excinfo:
        call_with_retry_pool(lambda s, r: calls.append(1), pool)
    assert excinfo.value.status() == "UNAVAILABLE"
    assert calls == []  # shed with zero I/O
    assert robust.exhausted_total() >= 1


def test_hedge_budget_is_enforced():
    pool = EndpointPool(["a", "b"], hedge_max_ratio=0.10,
                        hedge_delay_min_ms=1.0, explore_ratio=0.0)
    # 100 requests: the budget admits at most 10 hedges
    for _ in range(100):
        pool.note_request()
    granted = 0
    while pool.try_acquire_hedge(exclude={"a"}) is not None:
        granted += 1
    assert granted == 10
    assert pool.stats()["hedges_fired"] == 10
    # zero-budget pool never hedges
    pool0 = EndpointPool(["a", "b"], hedge_max_ratio=0.0)
    pool0.note_request()
    assert pool0.try_acquire_hedge() is None


def test_hedged_call_first_success_wins():
    pool = EndpointPool(["slow", "fast"], hedge_delay_min_ms=5.0,
                        hedge_max_ratio=1.0, explore_ratio=0.0)
    # pin routing to the slow endpoint so the hedge must rescue it
    pool.endpoints["slow"].ewma_latency_s = 0.0001
    pool.endpoints["fast"].ewma_latency_s = 0.001

    def fn(state, remaining):
        if state.url == "slow":
            time.sleep(0.25)
            return "slow"
        return "fast"

    start = time.monotonic()
    result = call_with_retry_pool(fn, pool)
    elapsed = time.monotonic() - start
    assert result == "fast"
    assert elapsed < 0.2  # did not wait out the slow primary
    stats = pool.stats()
    assert stats["hedges_fired"] == 1
    assert stats["hedges_won"] == 1
    # the slow loser is discarded and counted once it completes
    deadline = time.monotonic() + 2
    while pool.stats()["hedges_discarded"] == 0 \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert pool.stats()["hedges_discarded"] == 1


def test_sequences_never_hedge():
    pool = EndpointPool(["a", "b"], hedge_delay_min_ms=1.0,
                        hedge_max_ratio=1.0, explore_ratio=0.0)

    def fn(state, remaining):
        time.sleep(0.03)  # well past the hedge delay
        return state.url

    for _ in range(5):
        call_with_retry_pool(fn, pool, sequence_id=9)
    assert pool.stats()["hedges_fired"] == 0


def test_sticky_sequence_pins_until_ejection():
    pool = EndpointPool(["a", "b"], hedge_max_ratio=0.0, explore_ratio=0.0)
    seen = []

    def fn(state, remaining):
        seen.append(state.url)
        return state.url

    for _ in range(8):
        call_with_retry_pool(fn, pool, sequence_id=42)
    assert len(set(seen)) == 1
    pinned = seen[0]
    other = "b" if pinned == "a" else "a"
    # eject the pinned endpoint: the sequence re-pins (counted as a
    # failover) and stays on the survivor
    for _ in range(pool.endpoints[pinned].breaker.failure_threshold):
        pool.endpoints[pinned].breaker.record_failure()
    seen.clear()
    for _ in range(4):
        call_with_retry_pool(fn, pool, sequence_id=42)
    assert set(seen) == {other}
    assert pool.stats()["failovers"] >= 1
    # sequence_end releases the pin
    call_with_retry_pool(fn, pool, sequence_id=42, sequence_end=True)
    with pool._lock:
        assert 42 not in pool._sticky


def test_sequence_pin_released_on_terminal_failure():
    """A sequence whose FINAL request (sequence_end) fails terminally
    must still release the sticky pin — a leaked pin would grow the
    map forever and stale-route a reused sequence_id."""
    pool = EndpointPool(["a", "b"], hedge_max_ratio=0.0, explore_ratio=0.0)

    def bad(state, remaining):
        raise InferenceServerException("bad", status="INVALID_ARGUMENT")

    call_with_retry_pool(lambda s, r: s.url, pool, sequence_id=13)
    with pool._lock:
        assert 13 in pool._sticky
    with pytest.raises(InferenceServerException):
        call_with_retry_pool(bad, pool, sequence_id=13, sequence_end=True)
    with pool._lock:
        assert 13 not in pool._sticky


def test_sticky_failover_counted_once():
    """One sequence failover event = one failover count: the retry
    loop's count and pick()'s re-pin detector must not double-book."""
    pool = EndpointPool(["a", "b"], hedge_max_ratio=0.0, explore_ratio=0.0)
    calls = []

    def fn(state, remaining):
        calls.append(state.url)
        if len(calls) <= 3 or state.url == calls[0]:
            if len(calls) == 3:  # third step: pinned endpoint dies
                raise InferenceServerException("down",
                                               status="UNAVAILABLE")
        return state.url

    policy = RetryPolicy(max_attempts=3, initial_backoff_s=0.001)
    call_with_retry_pool(fn, pool, policy, sequence_id=21)
    call_with_retry_pool(fn, pool, policy, sequence_id=21)
    call_with_retry_pool(fn, pool, policy, sequence_id=21)  # fails over
    assert pool.stats()["failovers"] == 1


def test_prober_readmits_recovered_endpoint():
    healthy = {"v": False}
    pool = EndpointPool(
        ["z"],
        breaker_factory=lambda: CircuitBreaker(failure_threshold=1,
                                               reset_timeout_s=0.05),
        probe_interval_s=0.05)

    def down(state, remaining):
        raise InferenceServerException("down", status="UNAVAILABLE")

    with pytest.raises(InferenceServerException):
        call_with_retry_pool(down, pool)
    assert pool.stats()["ejections"] == 1
    pool.ensure_prober(lambda url: healthy["v"])
    time.sleep(0.25)  # failing probes keep it open
    assert pool.endpoints["z"].breaker.state == CircuitBreaker.OPEN
    healthy["v"] = True
    deadline = time.monotonic() + 5
    while pool.endpoints["z"].breaker.state != CircuitBreaker.CLOSED \
            and time.monotonic() < deadline:
        time.sleep(0.02)
    stats = pool.stats()
    pool.close()
    assert stats["readmissions"] == 1
    assert stats["probes"] >= 1


# -- Retry-After honored --------------------------------------------------


def test_retry_after_floors_the_backoff():
    sleeps = []

    def flaky(remaining):
        if not sleeps:
            error = InferenceServerException("busy", status="503")
            error.retry_after_s = 0.5
            raise error
        return "ok"

    policy = RetryPolicy(max_attempts=3, initial_backoff_s=0.001,
                         max_backoff_s=1.0)
    assert call_with_retry(flaky, policy, sleep=sleeps.append) == "ok"
    assert len(sleeps) == 1
    assert sleeps[0] >= 0.5  # server-advised minimum, not the 1ms draw


def test_retry_after_capped_by_backoff_max():
    sleeps = []

    def flaky(remaining):
        if not sleeps:
            error = InferenceServerException("busy", status="503")
            error.retry_after_s = 60.0  # hostile/huge header
            raise error
        return "ok"

    policy = RetryPolicy(max_attempts=3, initial_backoff_s=0.001,
                         max_backoff_s=0.2)
    assert call_with_retry(flaky, policy, sleep=sleeps.append) == "ok"
    assert sleeps[0] == pytest.approx(0.2)


def test_http_raise_if_error_carries_retry_after():
    from client_tpu.http import _endpoints as ep

    with pytest.raises(InferenceServerException) as excinfo:
        ep.raise_if_error(503, b'{"error": "saturated"}',
                          retry_after_s=ep.parse_retry_after("1"))
    assert excinfo.value.status() == "503"
    assert robust.retry_after_of(excinfo.value) == 1.0
    assert ep.parse_retry_after("bogus") is None
    assert ep.parse_retry_after(None) is None


def test_grpc_error_carries_retry_after_from_trailing_metadata():
    import grpc

    from client_tpu.grpc._utils import get_error_grpc

    class FakeRpcError(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.UNAVAILABLE

        def details(self):
            return "saturated"

        def trailing_metadata(self):
            return (("retry-after", "1"),)

    error = get_error_grpc(FakeRpcError())
    assert error.status() == "UNAVAILABLE"
    assert robust.retry_after_of(error) == 1.0


def test_grpc_server_sends_retry_after_on_unavailable():
    """End to end: a saturated gRPC server's UNAVAILABLE carries the
    retry-after trailing-metadata hint, and the client surfaces it."""
    from client_tpu.server.app import build_core, start_grpc_server
    from client_tpu.server.model import ServedModel, TensorSpec

    import client_tpu.grpc as grpcclient

    class Gated(ServedModel):
        max_batch_size = 4
        dynamic_batching = True
        pipeline_depth = 1
        max_queue_size = 1
        max_queue_delay_us = 1000

        def __init__(self):
            super().__init__()
            self.name = "gated_ra"
            self.inputs = [TensorSpec("IN", "FP32", [4])]
            self.outputs = [TensorSpec("OUT", "FP32", [4])]
            self.gate = threading.Event()

        def infer(self, inputs, parameters=None):
            self.gate.wait(30)
            return {"OUT": np.asarray(inputs["IN"])}

    core = build_core([])
    model = Gated()
    core.repository.add_model(model)
    handle = start_grpc_server(core=core, address="127.0.0.1:0")
    try:
        with grpcclient.InferenceServerClient(handle.address) as client:
            inputs = [grpcclient.InferInput("IN", [1, 4], "FP32")]
            inputs[0].set_data_from_numpy(np.ones((1, 4), np.float32))
            def saturate():
                # Keep the 1-deep queue occupied no matter how the
                # batcher interleaves gather and enqueue: depending on
                # scheduling, the gather can drain every admitted
                # request into the executing batch while the rest shed
                # at enqueue — leaving the queue EMPTY for the whole
                # gate, so every probe below is admitted and expires
                # DEADLINE_EXCEEDED instead of shedding. A shed
                # saturator re-submits until it is admitted (or the
                # gate opens), so probes always race a full queue.
                while not model.gate.is_set():
                    try:
                        client.infer("gated_ra", inputs)
                        return
                    except Exception:  # noqa: BLE001 — shed: retry
                        time.sleep(0.005)

            threads = [threading.Thread(target=saturate, daemon=True)
                       for _ in range(6)]
            for thread in threads:
                thread.start()
            time.sleep(0.3)  # saturate the 1-deep queue
            saw = None
            deadline = time.monotonic() + 10
            while saw is None and time.monotonic() < deadline:
                try:
                    # 200ms server-side queue deadline: an ADMITTED
                    # probe expires quickly instead of blocking on the
                    # gated model.
                    client.infer("gated_ra", inputs, timeout=200_000)
                except InferenceServerException as e:
                    if e.status() == "UNAVAILABLE":
                        saw = robust.retry_after_of(e)
                        break
                time.sleep(0.02)
            model.gate.set()
            for thread in threads:
                thread.join(timeout=10)
        # delta-seconds; since the QoS PR the value is the server's
        # gather-window estimate rather than a flat 1s
        assert saw is not None and saw > 0, \
            "UNAVAILABLE must carry the retry-after hint"
    finally:
        handle.stop()


# -- two real servers: kill, spike, stickiness ----------------------------


def _make_inputs(mod):
    i0 = mod.InferInput("INPUT0", [16], "INT32")
    i1 = mod.InferInput("INPUT1", [16], "INT32")
    i0.set_data_from_numpy(np.arange(16, dtype=np.int32))
    i1.set_data_from_numpy(np.ones(16, np.int32))
    return [i0, i1]


def _http_fleet(n=2):
    from client_tpu.server.app import build_core
    from client_tpu.server.http_server import start_http_server_thread

    members = []
    for i in range(n):
        core = build_core(["simple"])
        core.chaos_scope = "test_ep%d" % i
        runner = start_http_server_thread(core, host="127.0.0.1", port=0)
        members.append((core, runner))
    urls = ",".join("127.0.0.1:%d" % r.port for _c, r in members)
    return members, urls


def test_endpoint_kill_mid_load_zero_errors():
    import client_tpu.http as httpclient

    members, urls = _http_fleet()
    client = httpclient.InferenceServerClient(
        urls, concurrency=8,
        retry_policy=RetryPolicy(max_attempts=4, initial_backoff_s=0.01))
    errors, done, stop = [], [0], threading.Event()

    def worker():
        inputs = _make_inputs(httpclient)
        while not stop.is_set():
            try:
                result = client.infer("simple", inputs)
                assert result.as_numpy("OUTPUT0") is not None
                done[0] += 1
            except Exception as e:  # noqa: BLE001 — counted below
                errors.append(e)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(4)]
    try:
        for thread in threads:
            thread.start()
        time.sleep(0.6)
        members[0][1].stop()  # hard kill one of two endpoints
        members[0][0].shutdown()
        time.sleep(1.2)
    finally:
        stop.set()
        for thread in threads:
            thread.join(timeout=15)
    stats = client.pool_stats()
    client.close()
    members[1][1].stop()
    members[1][0].shutdown()
    assert done[0] > 50
    assert not errors, "failover must mask the outage: %r" % errors[:3]
    assert stats["failovers"] >= 1
    assert stats["ejections"] >= 1
    # all post-kill traffic landed on the survivor
    states = {e["url"]: e["state"] for e in stats["endpoints"]}
    assert "open" in states.values()


def test_latency_spike_hedge_wins_and_p99_bounded():
    """One fleet member latency-spiked by 800ms over real HTTP
    servers: requests FORCED onto the spiked endpoint must be rescued
    by hedges well under the spike. Exposure is pinned (the spiked
    endpoint's EWMA is reset before each request so routing picks it)
    to keep the test deterministic — the statistical p99 comparison
    under organic routing lives in the bench stage's failover_hedging
    extras and the perf-harness --degrade-one flow, where window
    lengths make it stable."""
    from client_tpu.server import chaos

    import client_tpu.http as httpclient

    members, urls = _http_fleet()
    spike_s = 0.8
    pool = EndpointPool(urls, hedge_delay_min_ms=30.0, hedge_max_ratio=1.0,
                        explore_ratio=0.0)
    client = httpclient.InferenceServerClient(
        urls, concurrency=8, endpoint_pool=pool,
        retry_policy=RetryPolicy(max_attempts=3, initial_backoff_s=0.01))
    spiked_url, fast_url = pool.urls
    try:
        inputs = _make_inputs(httpclient)
        for _ in range(40):  # warm the latency window with honest samples
            client.infer("simple", inputs)
        chaos.configure_scope(
            "test_ep0", chaos.ChaosConfig(latency_ms=spike_s * 1000.0))
        latencies = []
        for _ in range(8):
            # pin routing onto the spiked endpoint for this request
            with pool._lock:
                pool.endpoints[spiked_url].ewma_latency_s = 1e-5
                pool.endpoints[fast_url].ewma_latency_s = 0.01
            start = time.monotonic()
            client.infer("simple", inputs)
            latencies.append(time.monotonic() - start)
        stats = client.pool_stats()
        # every spiked request was rescued by its hedge: nothing waited
        # out the full spike, and the hedge actually won
        assert max(latencies) < spike_s * 0.8, \
            "hedge did not rescue: %s" % [round(lat, 3)
                                          for lat in latencies]
        assert stats["hedges_fired"] >= 8
        assert stats["hedges_won"] >= 6
        assert stats["hedge_delay_ms"] < spike_s * 1000.0 / 2
    finally:
        chaos.configure_scope("test_ep0", None)
        client.close()
        for core, runner in members:
            runner.stop()
            core.shutdown()


def test_sequence_sticky_across_fleet_and_failover():
    import client_tpu.http as httpclient

    members, urls = _http_fleet()
    client = httpclient.InferenceServerClient(
        urls, concurrency=4,
        retry_policy=RetryPolicy(max_attempts=4, initial_backoff_s=0.01))
    try:
        inputs = _make_inputs(httpclient)
        for step in range(10):
            client.infer("simple", inputs, sequence_id=7,
                         sequence_start=step == 0)
        # all 10 steps landed on ONE server
        counts = [
            core.model_statistics("simple").model_stats[0].inference_count
            for core, _r in members
        ]
        assert sorted(counts) == [0, 10], counts
        pinned_idx = counts.index(10)
        # kill the pinned endpoint: the sequence fails over and stays
        # pinned to the survivor, with zero client-visible errors
        members[pinned_idx][1].stop()
        members[pinned_idx][0].shutdown()
        for _ in range(10):
            client.infer("simple", inputs, sequence_id=7)
        survivor = members[1 - pinned_idx][0]
        count = survivor.model_statistics(
            "simple").model_stats[0].inference_count
        assert count == 10
        assert client.pool_stats()["failovers"] >= 1
    finally:
        client.close()
        for core, runner in members:
            try:
                runner.stop()
                core.shutdown()
            except Exception:
                pass


def test_ejection_then_prober_readmission_over_http():
    import client_tpu.http as httpclient
    from client_tpu.server.app import build_core
    from client_tpu.server.http_server import start_http_server_thread

    core1 = build_core(["simple"])
    runner1 = start_http_server_thread(core1, host="127.0.0.1", port=0)
    port1 = runner1.port
    core2 = build_core(["simple"])
    runner2 = start_http_server_thread(core2, host="127.0.0.1", port=0)
    urls = "127.0.0.1:%d,127.0.0.1:%d" % (port1, runner2.port)
    pool = EndpointPool(
        urls,
        breaker_factory=lambda: CircuitBreaker(failure_threshold=2,
                                               reset_timeout_s=0.1),
        probe_interval_s=0.1, hedge_max_ratio=0.0, explore_ratio=0.0)
    client = httpclient.InferenceServerClient(
        urls, concurrency=4, endpoint_pool=pool,
        retry_policy=RetryPolicy(max_attempts=4, initial_backoff_s=0.01))
    revived = None
    try:
        inputs = _make_inputs(httpclient)
        runner1.stop()  # endpoint 1 dies
        for _ in range(8):
            client.infer("simple", inputs)  # failures eject it
        assert pool.stats()["ejections"] >= 1
        url1 = "127.0.0.1:%d" % port1
        assert pool.endpoints[url1].breaker.state == CircuitBreaker.OPEN
        # replica comes back on the SAME address: the prober readmits
        # it without any client traffic sacrificed
        revived = start_http_server_thread(core1, host="127.0.0.1",
                                           port=port1)
        deadline = time.monotonic() + 10
        while pool.endpoints[url1].breaker.state != CircuitBreaker.CLOSED \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert pool.endpoints[url1].breaker.state == CircuitBreaker.CLOSED
        assert pool.stats()["readmissions"] >= 1
        client.infer("simple", inputs)  # traffic flows again
    finally:
        client.close()
        for runner in (runner2, revived):
            if runner is not None:
                try:
                    runner.stop()
                except Exception:
                    pass
        core1.shutdown()
        core2.shutdown()


# -- graceful unload drain ------------------------------------------------


class SlowUnloadModel:
    """Slow model that records when unload() fires relative to the
    in-flight request."""


def test_graceful_unload_drains_inflight_first():
    from client_tpu.server.app import build_core
    from client_tpu.server.model import ServedModel, TensorSpec
    from client_tpu.grpc._utils import get_inference_request

    import client_tpu.grpc as grpcclient  # for InferInput

    events = []

    class Slow(ServedModel):
        def __init__(self):
            super().__init__()
            self.name = "slow_unload"
            self.inputs = [TensorSpec("IN", "FP32", [2])]
            self.outputs = [TensorSpec("OUT", "FP32", [2])]

        def infer(self, inputs, parameters=None):
            events.append("infer_start")
            time.sleep(0.5)
            events.append("infer_done")
            return {"OUT": np.asarray(inputs["IN"])}

        def unload(self):
            events.append("unload")

    core = build_core([])
    core.repository.add_model(Slow())
    inputs = [grpcclient.InferInput("IN", [2], "FP32")]
    inputs[0].set_data_from_numpy(np.ones(2, np.float32))
    request = get_inference_request(model_name="slow_unload",
                                    inputs=inputs)
    results = {}

    def run_infer():
        try:
            core.infer(request)
            results["infer"] = "ok"
        except InferenceServerException as e:
            results["infer"] = e.status()

    infer_thread = threading.Thread(target=run_infer, daemon=True)
    infer_thread.start()
    deadline = time.monotonic() + 5
    while "infer_start" not in events and time.monotonic() < deadline:
        time.sleep(0.01)
    assert core.repository.inflight("slow_unload") == 1

    unload_thread = threading.Thread(
        target=lambda: core.unload_model("slow_unload"), daemon=True)
    unload_thread.start()
    time.sleep(0.1)  # drain has begun, request still in flight
    # new requests are shed with UNAVAILABLE (-> HTTP 503 + Retry-After)
    with pytest.raises(InferenceServerException) as excinfo:
        core.infer(request)
    assert excinfo.value.status() == "UNAVAILABLE"
    infer_thread.join(timeout=10)
    unload_thread.join(timeout=10)
    # the in-flight request completed, and teardown came strictly after
    assert results["infer"] == "ok"
    assert events == ["infer_start", "infer_done", "unload"]
    assert core.repository.inflight("slow_unload") == 0
    # fully gone now
    with pytest.raises(InferenceServerException) as excinfo:
        core.infer(request)
    assert excinfo.value.status() == "NOT_FOUND"


def test_unload_drain_is_bounded():
    from client_tpu.server.app import build_core
    from client_tpu.server.model import ServedModel, TensorSpec
    from client_tpu.grpc._utils import get_inference_request

    import client_tpu.grpc as grpcclient

    gate = threading.Event()

    class Wedged(ServedModel):
        def __init__(self):
            super().__init__()
            self.name = "wedged"
            self.inputs = [TensorSpec("IN", "FP32", [2])]
            self.outputs = [TensorSpec("OUT", "FP32", [2])]

        def infer(self, inputs, parameters=None):
            gate.wait(30)
            return {"OUT": np.asarray(inputs["IN"])}

    core = build_core([])
    core.repository.add_model(Wedged())
    inputs = [grpcclient.InferInput("IN", [2], "FP32")]
    inputs[0].set_data_from_numpy(np.ones(2, np.float32))
    request = get_inference_request(model_name="wedged", inputs=inputs)
    thread = threading.Thread(
        target=lambda: _swallow(lambda: core.infer(request)), daemon=True)
    thread.start()
    time.sleep(0.2)
    start = time.monotonic()
    core.repository.begin_unload("wedged")
    core.repository.finish_unload("wedged", drain_timeout_s=0.3)
    elapsed = time.monotonic() - start
    assert elapsed < 2.0, "drain must be bounded, took %.1fs" % elapsed
    gate.set()
    thread.join(timeout=10)


def _swallow(fn):
    try:
        fn()
    except Exception:
        pass


# -- asyncio clients over a fleet -----------------------------------------


def test_http_aio_pool_failover():
    import asyncio

    import client_tpu.http.aio as aioclient

    members, urls = _http_fleet()

    async def main():
        client = aioclient.InferenceServerClient(
            urls,
            retry_policy=RetryPolicy(max_attempts=4,
                                     initial_backoff_s=0.01))
        try:
            inputs = _make_inputs(aioclient)
            for _ in range(10):
                await client.infer("simple", inputs)
            members[0][1].stop()  # kill one endpoint
            members[0][0].shutdown()
            for _ in range(10):
                result = await client.infer("simple", inputs)
                assert result.as_numpy("OUTPUT0") is not None
            return client.pool_stats()
        finally:
            await client.close()

    stats = asyncio.run(main())
    members[1][1].stop()
    members[1][0].shutdown()
    assert stats["requests"] >= 20


def test_grpc_aio_pool_failover():
    import asyncio

    from client_tpu.server.app import build_core, start_grpc_server

    import client_tpu.grpc.aio as aioclient

    core1 = build_core(["simple"])
    core2 = build_core(["simple"])
    handle1 = start_grpc_server(core=core1, address="127.0.0.1:0")
    handle2 = start_grpc_server(core=core2, address="127.0.0.1:0")

    async def main():
        client = aioclient.InferenceServerClient(
            "%s,%s" % (handle1.address, handle2.address),
            retry_policy=RetryPolicy(max_attempts=4,
                                     initial_backoff_s=0.01))
        try:
            inputs = _make_inputs(aioclient)
            for _ in range(10):
                await client.infer("simple", inputs)
            handle1.stop()
            for _ in range(10):
                result = await client.infer("simple", inputs)
                assert result.as_numpy("OUTPUT0") is not None
            return client.pool_stats()
        finally:
            await client.close()

    stats = asyncio.run(main())
    handle2.stop()
    assert stats["requests"] >= 20
