"""End-to-end HTTP/REST integration tests (binary tensor protocol +
pure-JSON path) against the in-process server."""

import json

import numpy as np
import pytest

import client_tpu.http as httpclient
from client_tpu.server.app import build_core
from client_tpu.server.http_server import start_http_server_thread
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def server():
    core = build_core(["simple", "add_sub_fp32", "add_sub_large"])
    runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield runner
    runner.stop()


@pytest.fixture()
def client(server):
    with httpclient.InferenceServerClient(
        "127.0.0.1:%d" % server.port, concurrency=4
    ) as c:
        yield c


def _simple_inputs():
    in0 = np.arange(16, dtype=np.int32)
    in1 = np.ones(16, dtype=np.int32)
    inputs = [
        httpclient.InferInput("INPUT0", [16], "INT32"),
        httpclient.InferInput("INPUT1", [16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(in0)
    inputs[1].set_data_from_numpy(in1)
    return in0, in1, inputs


def test_infer_multi_megabyte_tensors(client):
    """4 MiB per tensor through the HTTP binary protocol: the 8 MiB
    request/response bodies exercise chunked socket I/O and the
    Inference-Header-Content-Length split on large payloads."""
    n = 1 << 20
    x = (np.arange(n, dtype=np.float32) % 9973)
    y = (np.arange(n, dtype=np.float32) % 7919)
    inputs = [
        httpclient.InferInput("INPUT0", [n], "FP32").set_data_from_numpy(x),
        httpclient.InferInput("INPUT1", [n], "FP32").set_data_from_numpy(y),
    ]
    result = client.infer("add_sub_large", inputs)
    np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), x + y)
    np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), x - y)


def test_infer_json_tensor_data(client):
    """binary_data=False on inputs and outputs: tensors ride as JSON
    data arrays both ways (no binary extension anywhere on the wire) —
    the interop mode for KServe servers without the binary protocol
    (parity: reference HTTP client's binary_data kwargs)."""
    x = np.arange(16, dtype=np.float32) / 3.0
    y = np.ones(16, dtype=np.float32) * 2.5
    inputs = [
        httpclient.InferInput("INPUT0", [16], "FP32").set_data_from_numpy(
            x, binary_data=False),
        httpclient.InferInput("INPUT1", [16], "FP32").set_data_from_numpy(
            y, binary_data=False),
    ]
    outputs = [
        httpclient.InferRequestedOutput("OUTPUT0", binary_data=False),
        httpclient.InferRequestedOutput("OUTPUT1", binary_data=False),
    ]
    result = client.infer("add_sub_fp32", inputs, outputs=outputs)
    np.testing.assert_allclose(result.as_numpy("OUTPUT0"), x + y, rtol=1e-6)
    np.testing.assert_allclose(result.as_numpy("OUTPUT1"), x - y, rtol=1e-6)


def test_json_tensor_bytes_must_be_utf8(client):
    """binary_data=False on a BYTES input holding non-UTF-8 bytes must
    error loudly — a JSON string cannot carry arbitrary binary, and a
    lossy re-encode would silently corrupt the payload."""
    arr = np.array([b"\xff\xfe raw"], dtype=np.object_)
    infer_input = httpclient.InferInput("INPUT0", [1], "BYTES")
    infer_input.set_data_from_numpy(arr, binary_data=False)
    with pytest.raises(InferenceServerException, match="non-UTF-8"):
        client.infer("simple_string", [infer_input])


def test_health(client):
    assert client.is_server_live()
    assert client.is_server_ready()
    assert client.is_model_ready("simple")
    assert not client.is_model_ready("ghost")


def test_metadata(client):
    meta = client.get_server_metadata()
    assert meta["name"] == "client_tpu_server"
    model_meta = client.get_model_metadata("simple")
    assert model_meta["name"] == "simple"
    assert model_meta["inputs"][0]["datatype"] == "INT32"
    config = client.get_model_config("simple")
    assert config["name"] == "simple"


def test_metadata_unknown_model(client):
    with pytest.raises(InferenceServerException) as exc:
        client.get_model_metadata("ghost")
    assert exc.value.status() == "404"


def test_infer_binary(client):
    in0, in1, inputs = _simple_inputs()
    outputs = [
        httpclient.InferRequestedOutput("OUTPUT0", binary_data=True),
        httpclient.InferRequestedOutput("OUTPUT1", binary_data=True),
    ]
    result = client.infer("simple", inputs, outputs=outputs, request_id="7")
    assert result.get_response()["id"] == "7"
    np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
    np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), in0 - in1)


def test_infer_json_outputs(client):
    in0, in1, inputs = _simple_inputs()
    outputs = [
        httpclient.InferRequestedOutput("OUTPUT0", binary_data=False),
    ]
    result = client.infer("simple", inputs, outputs=outputs)
    out = result.get_output("OUTPUT0")
    assert out["data"] == list(range(1, 17))
    np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)


def test_infer_default_outputs(client):
    in0, in1, inputs = _simple_inputs()
    result = client.infer("simple", inputs)
    np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
    np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), in0 - in1)


def test_infer_pure_json_request(server):
    """A raw JSON request with 'data' lists (no binary extension) —
    what curl or non-binary v2 clients send."""
    import http.client as hc

    conn = hc.HTTPConnection("127.0.0.1", server.port)
    body = json.dumps({
        "inputs": [
            {"name": "INPUT0", "shape": [16], "datatype": "INT32",
             "data": list(range(16))},
            {"name": "INPUT1", "shape": [16], "datatype": "INT32",
             "data": [1] * 16},
        ]
    })
    conn.request("POST", "/v2/models/simple/infer", body=body,
                 headers={"Content-Type": "application/json"})
    response = conn.getresponse()
    payload = json.loads(response.read())
    conn.close()
    assert response.status == 200
    by_name = {o["name"]: o for o in payload["outputs"]}
    assert by_name["OUTPUT0"]["data"] == list(range(1, 17))
    assert by_name["OUTPUT1"]["data"] == [i - 1 for i in range(16)]


def test_infer_error(client):
    _, _, inputs = _simple_inputs()
    with pytest.raises(InferenceServerException) as exc:
        client.infer("ghost", inputs)
    assert "unknown model" in str(exc.value)


def test_async_infer(client):
    in0, in1, inputs = _simple_inputs()
    handles = [client.async_infer("simple", inputs) for _ in range(8)]
    for handle in handles:
        result = handle.get_result(timeout=10)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)


def test_async_infer_error(client):
    _, _, inputs = _simple_inputs()
    handle = client.async_infer("ghost", inputs)
    with pytest.raises(InferenceServerException):
        handle.get_result(timeout=10)


def test_generate_and_parse_body_statics(client):
    in0, in1, inputs = _simple_inputs()
    body, json_len = httpclient.InferenceServerClient.generate_request_body(
        inputs, outputs=[httpclient.InferRequestedOutput("OUTPUT0")]
    )
    assert json_len is not None and json_len < len(body)
    result = client.infer("simple", inputs)
    # round-trip: re-parse by serializing through the wire helpers
    assert result.as_numpy("OUTPUT0") is not None


def test_statistics_and_repository(client):
    _, _, inputs = _simple_inputs()
    client.infer("simple", inputs)
    stats = client.get_inference_statistics("simple")
    assert stats["model_stats"][0]["name"] == "simple"
    index = client.get_model_repository_index()
    names = {m["name"] for m in index}
    assert "simple" in names
    client.load_model("add_sub")
    assert client.is_model_ready("add_sub")
    client.unload_model("add_sub")
    assert not client.is_model_ready("add_sub")


def test_trace_log_settings(client):
    settings = client.update_trace_settings(
        settings={"trace_level": ["TIMESTAMPS"]}
    )
    assert settings["trace_level"] == "TIMESTAMPS"
    log = client.update_log_settings({"log_verbose_level": 3})
    assert log["log_verbose_level"] == 3


def test_system_shm_http(client):
    import client_tpu.utils.shared_memory as shm

    in0 = np.arange(16, dtype=np.int32)
    in1 = np.full(16, 5, dtype=np.int32)
    byte_size = in0.nbytes
    handles = []
    try:
        for name, arr in (("h_in0", in0), ("h_in1", in1)):
            handle = shm.create_shared_memory_region(name, "/ct_h_" + name,
                                                     byte_size)
            shm.set_shared_memory_region(handle, [arr])
            client.register_system_shared_memory(name, "/ct_h_" + name,
                                                 byte_size)
            handles.append(handle)
        status = client.get_system_shared_memory_status()
        assert {r["name"] for r in status} >= {"h_in0", "h_in1"}

        inputs = [
            httpclient.InferInput("INPUT0", [16], "INT32"),
            httpclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_shared_memory("h_in0", byte_size)
        inputs[1].set_shared_memory("h_in1", byte_size)
        result = client.infer("simple", inputs)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
    finally:
        client.unregister_system_shared_memory()
        for handle in handles:
            shm.destroy_shared_memory_region(handle)


def test_bytes_tensor_http(server):
    """BYTES round trip through JSON data on a model that echoes?
    simple model is INT32 — test BYTES through wire helpers only."""
    from client_tpu.protocol.http_wire import (
        decode_infer_request,
        encode_infer_request,
    )
    from client_tpu._infer_common import InferInput

    arr = np.array([b"hello", b"world"], dtype=np.object_)
    inp = InferInput("S", [2], "BYTES").set_data_from_numpy(arr)
    body, json_len = encode_infer_request([inp])
    request = decode_infer_request(body, "m", "", json_len)
    assert request.raw_input_contents[0] == (
        b"\x05\x00\x00\x00hello\x05\x00\x00\x00world"
    )
