"""Unit tests for the transport-neutral data model."""

import numpy as np
import pytest
import ml_dtypes

from client_tpu._infer_common import (
    InferInput,
    InferRequestedOutput,
    build_request_parameters,
)
from client_tpu.utils import InferenceServerException


def test_infer_input_numpy():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    inp = InferInput("in0", [2, 3], "FP32")
    inp.set_data_from_numpy(x)
    assert inp.raw_data() == x.tobytes()
    assert inp.shape() == [2, 3]
    inp.validate()


def test_infer_input_dtype_mismatch():
    inp = InferInput("in0", [2], "FP32")
    with pytest.raises(InferenceServerException, match="unexpected datatype"):
        inp.set_data_from_numpy(np.zeros(2, dtype=np.int32))


def test_infer_input_shape_mismatch():
    inp = InferInput("in0", [2, 3], "FP32")
    with pytest.raises(InferenceServerException, match="unexpected numpy array shape"):
        inp.set_data_from_numpy(np.zeros((3, 2), dtype=np.float32))


def test_infer_input_bytes():
    arr = np.array([b"ab", b"c"], dtype=np.object_)
    inp = InferInput("s", [2], "BYTES")
    inp.set_data_from_numpy(arr)
    assert inp.raw_data() == b"\x02\x00\x00\x00ab\x01\x00\x00\x00c"


def test_infer_input_bf16_from_float():
    inp = InferInput("b", [3], "BF16")
    inp.set_data_from_numpy(np.array([1, 2, 3], dtype=np.float32))
    assert len(inp.raw_data()) == 6
    out = np.frombuffer(inp.raw_data(), dtype=ml_dtypes.bfloat16)
    assert np.allclose(out.astype(np.float32), [1, 2, 3])


def test_infer_input_shared_memory():
    inp = InferInput("in0", [2, 2], "FP32")
    inp.set_shared_memory("region0", 16, offset=4)
    assert inp.shared_memory() == ("region0", 16, 4)
    assert inp.raw_data() is None
    inp.validate()
    # setting numpy data clears shm and vice versa
    inp.set_data_from_numpy(np.zeros((2, 2), dtype=np.float32))
    assert inp.shared_memory() is None
    inp.set_shared_memory("region0", 16)
    assert inp.raw_data() is None


def test_infer_input_no_data():
    with pytest.raises(InferenceServerException, match="has no data"):
        InferInput("in0", [1], "FP32").validate()


def test_infer_input_size_validation():
    inp = InferInput("in0", [4], "FP32")
    inp.set_data_from_numpy(np.zeros(4, dtype=np.float32))
    inp.set_shape([5])
    with pytest.raises(InferenceServerException, match="expected 20"):
        inp.validate()


def test_requested_output():
    out = InferRequestedOutput("out0", binary_data=False, class_count=3)
    assert out.name() == "out0"
    assert not out.binary_data()
    assert out.class_count() == 3
    out.set_shared_memory("r", 64)
    assert out.shared_memory() == ("r", 64, 0)
    out.unset_shared_memory()
    assert out.shared_memory() is None


def test_request_parameters():
    p = build_request_parameters(sequence_id=5, sequence_start=True, priority=2,
                                 timeout=100, parameters={"x": 1})
    assert p == {"sequence_id": 5, "sequence_start": True, "sequence_end": False,
                 "priority": 2, "timeout": 100, "x": 1}
    assert build_request_parameters() == {}
    with pytest.raises(InferenceServerException, match="reserved"):
        build_request_parameters(parameters={"priority": 1})
