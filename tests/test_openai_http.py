"""OpenAI-compatible endpoint tests (/v1/chat/completions and
/v1/completions over the LLM models) — the server-side counterpart of
the reference perf harness's openai client backend
(client_backend/openai/)."""

import json
import urllib.request

import pytest


@pytest.fixture(scope="module")
def llm_http_server():
    from client_tpu.server.app import build_core
    from client_tpu.server.http_server import start_http_server_thread

    core = build_core(["llm_tiny"])
    runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield "http://127.0.0.1:%d" % runner.port
    runner.stop()


def _post(url, body):
    request = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    return urllib.request.urlopen(request, timeout=120)


def test_chat_completion(llm_http_server):
    with _post(llm_http_server + "/v1/chat/completions", {
        "model": "llm_tiny", "max_tokens": 6,
        "messages": [{"role": "user", "content": "hello"}],
    }) as response:
        doc = json.loads(response.read())
    assert doc["object"] == "chat.completion"
    assert doc["model"] == "llm_tiny"
    choice = doc["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert choice["finish_reason"] == "stop"


def test_chat_completion_stream(llm_http_server):
    with _post(llm_http_server + "/v1/chat/completions", {
        "model": "llm_tiny", "max_tokens": 5, "stream": True,
        "messages": [{"role": "user", "content": "hi"}],
    }) as response:
        assert response.headers["Content-Type"].startswith(
            "text/event-stream")
        text = response.read().decode()
    events = [e for e in text.split("\n\n") if e.startswith("data: ")]
    assert events[-1] == "data: [DONE]"
    chunks = [json.loads(e[6:]) for e in events[:-1]]
    assert chunks, "no streamed chunks"
    for chunk in chunks:
        assert chunk["object"] == "chat.completion.chunk"
        assert "delta" in chunk["choices"][0]
    # The last data chunk is marked final.
    assert chunks[-1]["choices"][0]["finish_reason"] == "stop"


def test_text_completion(llm_http_server):
    with _post(llm_http_server + "/v1/completions", {
        "model": "llm_tiny", "max_tokens": 4, "prompt": "abc",
    }) as response:
        doc = json.loads(response.read())
    assert doc["object"] == "text_completion"
    assert "text" in doc["choices"][0]


def test_chat_completion_unknown_model(llm_http_server):
    with pytest.raises(urllib.error.HTTPError) as excinfo:
        _post(llm_http_server + "/v1/chat/completions", {
            "model": "no-such-model",
            "messages": [{"role": "user", "content": "x"}],
        })
    assert excinfo.value.code == 404


def test_chat_completion_missing_model(llm_http_server):
    with pytest.raises(urllib.error.HTTPError) as excinfo:
        _post(llm_http_server + "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "x"}],
        })
    assert excinfo.value.code == 400
