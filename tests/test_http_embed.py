"""Route-level tests for the embedded REST dispatcher
(client_tpu/server/http_embed.py) — the surface the native HTTP/1.1
front-end forwards into. Pure Python: no native binary needed."""

import json

import numpy as np
import pytest

from client_tpu.protocol.http_wire import (
    decode_infer_response,
    encode_infer_request,
)
from client_tpu.server import http_embed
from client_tpu.server.app import build_core


@pytest.fixture(scope="module")
def core():
    return build_core(["simple"])


def call(core, method, path, headers=None, body=b""):
    return http_embed.http_call(core, method, path, headers or {}, body)


def test_health_and_metadata(core):
    assert call(core, "GET", "/v2/health/live")[0] == 200
    assert call(core, "GET", "/v2/health/ready")[0] == 200
    assert call(core, "GET", "/v2/models/simple/ready")[0] == 200
    assert call(core, "GET", "/v2/models/nope/ready")[0] == 400
    status, headers, body = call(core, "GET", "/v2")
    assert status == 200
    assert json.loads(body)["name"] == "client_tpu_server"
    status, _, body = call(core, "GET", "/v2/models/simple")
    assert [t["name"] for t in json.loads(body)["inputs"]] == \
        ["INPUT0", "INPUT1"]
    assert call(core, "GET", "/v2/models/simple/config")[0] == 200


def test_error_mapping_and_unknown_route(core):
    status, _, body = call(core, "GET", "/v2/models/ghost")
    assert status == 404
    assert "error" in json.loads(body)
    assert call(core, "GET", "/v2/not/a/route")[0] == 404
    assert call(core, "POST", "/v2/health/live")[0] == 404  # wrong verb


def _infer_body():
    from client_tpu.http import InferInput

    a = np.arange(16, dtype=np.int32)
    b = np.ones(16, dtype=np.int32)
    inputs = [InferInput("INPUT0", [16], "INT32"),
              InferInput("INPUT1", [16], "INT32")]
    inputs[0].set_data_from_numpy(a)
    inputs[1].set_data_from_numpy(b)
    body, json_len = encode_infer_request(inputs)
    return a, b, body, json_len


def test_infer_binary_protocol(core):
    a, b, body, json_len = _infer_body()
    headers = {}
    if json_len is not None:
        headers["inference-header-content-length"] = str(json_len)
    status, reply_headers, payload = call(
        core, "POST", "/v2/models/simple/infer", headers, body)
    assert status == 200
    length = reply_headers.get("Inference-Header-Content-Length")
    _, outputs = decode_infer_response(payload,
                                       int(length) if length else None)
    decoded = outputs["OUTPUT0"]
    out = (np.frombuffer(decoded.raw, dtype=np.int32)
           if decoded.raw is not None
           else np.asarray(decoded.json_data, dtype=np.int32))
    np.testing.assert_array_equal(out, a + b)


def test_infer_response_compression(core):
    from client_tpu.protocol.http_wire import decompress_body

    a, b, body, json_len = _infer_body()
    headers = {"accept-encoding": "gzip"}
    if json_len is not None:
        headers["inference-header-content-length"] = str(json_len)
    status, reply_headers, payload = call(
        core, "POST", "/v2/models/simple/infer", headers, body)
    assert status == 200
    assert reply_headers.get("Content-Encoding") == "gzip"
    raw = decompress_body(payload, "gzip")
    length = reply_headers.get("Inference-Header-Content-Length")
    _, outputs = decode_infer_response(raw, int(length) if length else None)
    decoded = outputs["OUTPUT0"]
    out = (np.frombuffer(decoded.raw, dtype=np.int32)
           if decoded.raw is not None
           else np.asarray(decoded.json_data, dtype=np.int32))
    np.testing.assert_array_equal(out, a + b)


def test_system_shm_roundtrip(core):
    import client_tpu.utils.shared_memory as shm

    handle = shm.create_shared_memory_region("he_r", "/he_embed", 64)
    try:
        status, _, _ = call(
            core, "POST", "/v2/systemsharedmemory/region/he_r/register",
            body=json.dumps({"key": "/he_embed", "byte_size": 64}).encode())
        assert status == 200
        _, _, body = call(core, "GET", "/v2/systemsharedmemory/status")
        assert any(r["name"] == "he_r" for r in json.loads(body))
        assert call(core, "POST",
                    "/v2/systemsharedmemory/region/he_r/unregister")[0] \
            == 200
    finally:
        shm.destroy_shared_memory_region(handle)


def test_repository_index(core):
    status, _, body = call(core, "POST", "/v2/repository/index",
                           body=b'{"ready": true}')
    assert status == 200
    assert any(m["name"] == "simple" for m in json.loads(body))


def test_trace_and_logging_routes(core):
    status, _, body = call(core, "GET", "/v2/trace/setting")
    assert status == 200
    status, _, body = call(
        core, "POST", "/v2/trace/setting",
        body=json.dumps({"trace_level": ["TIMESTAMPS"]}).encode())
    assert status == 200
    assert "trace_level" in json.loads(body)
    status, _, body = call(core, "GET", "/v2/logging")
    assert status == 200
    status, _, body = call(core, "POST", "/v2/logging",
                           body=b'{"log_verbose_level": 1}')
    assert status == 200


def test_generate_route(core):
    assert call(core, "POST",
                "/v2/repository/models/simple_string/load")[0] == 200
    status, _, body = call(
        core, "POST", "/v2/models/simple_string/generate",
        body=json.dumps({"INPUT0": ["1"] * 16,
                         "INPUT1": ["2"] * 16}).encode())
    assert status == 200
    assert json.loads(body)["model_name"] == "simple_string"
