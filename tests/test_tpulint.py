"""tpulint: golden fixtures per checker (one violating, one clean, one
suppressed-with-reason), the baseline/suppression machinery, the
acceptance-criteria injections, and an end-to-end run over the real
tree asserting zero non-baselined findings."""

import pathlib
import shutil
import sys
import textwrap

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools import tpulint  # noqa: E402
from tools.tpulint import framework  # noqa: E402
from tools.tpulint.check_aio import check_aio_blocking  # noqa: E402
from tools.tpulint.check_drift import (  # noqa: E402
    _proto_syntax,
    check_metrics_doc_drift,
    check_proto_drift,
)
from tools.tpulint.check_locks import (  # noqa: E402
    check_lock_discipline,
    check_lock_order,
)
from tools.tpulint.check_pairing import check_resource_pairing  # noqa: E402
from tools.tpulint.check_status import (  # noqa: E402
    check_retry_after,
    check_status_literals,
)


def _source(tmp_path, code, rel="client_tpu/server/fixture.py"):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(code))
    return framework.SourceFile(path, tmp_path)


def _ids(findings):
    return [f.checker for f in findings]


# -- lock-discipline --------------------------------------------------------

def test_lock_discipline_violating(tmp_path):
    src = _source(tmp_path, """
        import threading, time

        class T:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(0.1)

            def bad_acquire_region(self, fut):
                self._lock.acquire()
                fut.result()
                self._lock.release()

            def bogus_timeouts(self, fut, work_queue):
                with self._lock:
                    fut.result(None)      # None bounds nothing
                    work_queue.get(True)  # True is the BLOCK flag
    """)
    findings = check_lock_discipline(src)
    assert len(findings) == 4
    assert all(f.checker == "lock-discipline" for f in findings)
    assert "time.sleep" in findings[0].message
    assert "self._lock" in findings[0].message
    assert "Future.result" in findings[1].message
    assert "Future.result" in findings[2].message
    assert "Queue.get" in findings[3].message


def test_lock_discipline_clean(tmp_path):
    src = _source(tmp_path, """
        import threading, time

        class T:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()

            def fine(self):
                with self._lock:
                    x = 1
                time.sleep(0.1)  # not under the lock
                return x

            def cv_idiom(self):
                # waiting on the innermost held cv releases it — the
                # standard condition-variable pattern is NOT flagged.
                with self._cv:
                    self._cv.wait()

            def bounded(self, fut):
                with self._lock:
                    return fut.result(timeout=1.0)
    """)
    assert check_lock_discipline(src) == []


def test_lock_discipline_try_finally_release_clears_held(tmp_path):
    # The canonical acquire/try/finally/release idiom: code AFTER the
    # Try no longer holds the lock and must not be flagged.
    src = _source(tmp_path, """
        import threading, time

        class T:
            def __init__(self):
                self._lock = threading.Lock()

            def idiom(self):
                self._lock.acquire()
                try:
                    x = 1
                finally:
                    self._lock.release()
                time.sleep(1)  # lock released above: clean
                return x
    """)
    assert check_lock_discipline(src) == []


def test_lock_discipline_nonblocking_get_clean(tmp_path):
    src = _source(tmp_path, """
        import threading

        class T:
            def __init__(self):
                self._lock = threading.Lock()

            def drain(self, work_queue):
                with self._lock:
                    return work_queue.get(False)  # raises Empty: clean
    """)
    assert check_lock_discipline(src) == []


def test_lock_discipline_wait_with_outer_lock_flagged(tmp_path):
    src = _source(tmp_path, """
        import threading

        class T:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()

            def deadlock_shape(self):
                with self._lock:
                    with self._cv:
                        self._cv.wait()
    """)
    findings = check_lock_discipline(src)
    assert len(findings) == 1 and "wait() without a timeout" \
        in findings[0].message


def test_lock_discipline_suppressed_with_reason(tmp_path):
    src = _source(tmp_path, """
        import threading, time

        class T:
            def __init__(self):
                self._lock = threading.Lock()

            def tolerated(self):
                with self._lock:
                    # tpulint: disable=lock-discipline -- bounded
                    # 1ms pacing sleep, measured harmless
                    time.sleep(0.001)
    """)
    findings = check_lock_discipline(src)
    assert [f for f in findings
            if not src.suppressed(f.checker, f.line)] == []
    assert src.bad_suppressions == []


# -- lock-order -------------------------------------------------------------

def test_lock_order_cycle_detected(tmp_path):
    src = _source(tmp_path, """
        import threading

        class T:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._b_lock:
                    self._helper()

            def _helper(self):
                with self._a_lock:
                    pass
    """)
    findings = check_lock_order([src])
    assert len(findings) == 1
    assert "lock-order cycle" in findings[0].message
    assert "_a_lock" in findings[0].message and \
        "_b_lock" in findings[0].message


def test_lock_order_clean_consistent_order(tmp_path):
    src = _source(tmp_path, """
        import threading

        class T:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    self._helper()

            def _helper(self):
                with self._b_lock:
                    pass
    """)
    assert check_lock_order([src]) == []


def test_lock_order_condition_alias_not_a_cycle(tmp_path):
    # A Condition wrapping a lock IS that lock; repository.py's
    # _lock/_cv pair must not read as an ordering edge.
    src = _source(tmp_path, """
        import threading

        class T:
            def __init__(self):
                self._lock = threading.RLock()
                self._cv = threading.Condition(self._lock)

            def one(self):
                with self._lock:
                    pass

            def two(self):
                with self._cv:
                    self._one_locked()

            def _one_locked(self):
                with self._lock:
                    pass
    """)
    assert check_lock_order([src]) == []


def test_lock_order_reentrant_nonreentrant_lock(tmp_path):
    src = _source(tmp_path, """
        import threading

        class T:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """)
    findings = check_lock_order([src])
    assert len(findings) == 1
    assert "re-acquires non-reentrant" in findings[0].message


# -- resource-pairing -------------------------------------------------------

def test_resource_pairing_violating(tmp_path):
    src = _source(tmp_path, """
        def leaky(quotas, work):
            token = quotas.acquire("tenant")
            work()           # raises -> token leaks (the PR-7 shape)
            quotas.release(token)
    """)
    findings = check_resource_pairing(src)
    assert _ids(findings) == ["resource-pairing"]
    assert "finally" in findings[0].message


def test_resource_pairing_nested_generator_not_excused(tmp_path):
    # A nested generator helper must not color the enclosing function
    # as a generator and excuse its unpaired acquire (review catch:
    # ast.walk's 'continue' does not prune subtrees).
    src = _source(tmp_path, """
        def leaky(quotas):
            def helper():
                yield 1
            token = quotas.acquire("tenant")
            return helper(), token
    """)
    assert _ids(check_resource_pairing(src)) == ["resource-pairing"]


def test_resource_pairing_clean(tmp_path):
    src = _source(tmp_path, """
        def safe(quotas, work):
            token = quotas.acquire("tenant")
            try:
                work()
            finally:
                quotas.release(token)

        class Admission:
            def __enter__(self):
                self._token = self.quotas.acquire("t")
                return self

            def __exit__(self, *exc):
                self.quotas.release(self._token)
    """)
    assert check_resource_pairing(src) == []


def test_resource_pairing_ledger_register_leak_flagged(tmp_path):
    # PR-15: ledger.register/release is the same guarantee class as
    # tenant admission — an unreleased register leaks an HBM
    # attribution row for the process lifetime.
    src = _source(tmp_path, """
        def leaky(ledger, build):
            row = ledger.register("m", "weights", 128)
            build()          # raises -> the row leaks
            ledger.release(row)
    """)
    findings = check_resource_pairing(src)
    assert _ids(findings) == ["resource-pairing"]
    assert "finally" in findings[0].message


def test_resource_pairing_ledger_no_release_at_all_flagged(tmp_path):
    src = _source(tmp_path, """
        def leaky(ledger):
            row = ledger.register("m", "weights", 128)
            return row.nbytes
    """)
    assert _ids(check_resource_pairing(src)) == ["resource-pairing"]


def test_resource_pairing_ledger_clean_forms(tmp_path):
    src = _source(tmp_path, """
        def finally_paired(ledger, build):
            row = ledger.register("m", "weights", 128)
            try:
                build()
            finally:
                ledger.release(row)

        def attribute_handoff(ledger, region):
            # Ownership parked on the owning object, whose teardown
            # releases it (the arena/replica pattern).
            region.ledger_row = ledger.register("arena", "regions", 64)

        def model_sweep_paired(ledger, teardown):
            row = ledger.register("m", "kv", 32)
            try:
                teardown()
            finally:
                ledger.release_model("m")
    """)
    assert check_resource_pairing(src) == []


def test_resource_pairing_ledger_replace_pattern_clean(tmp_path):
    # Dropping the PREVIOUS holder's row before registering the fresh
    # one is the replace pattern, not a pairing — the release above
    # the register must not be mistaken for its finally-less pairing
    # when the fresh handle is parked on an owner.
    src = _source(tmp_path, """
        def reload(ledger, measure):
            ledger.release_component("m", "weights")
            measure.row = ledger.register("m", "weights", 64)
    """)
    assert check_resource_pairing(src) == []


def test_resource_pairing_non_ledger_register_not_flagged(tmp_path):
    # `register` is a common verb (shm regions, prefix pages) — only
    # ledger-named receivers engage the pairing rule.
    src = _source(tmp_path, """
        def fine(memory):
            memory.register("region", "key", 0, 64)
    """)
    assert check_resource_pairing(src) == []


def test_resource_pairing_hbm_lease_leak_flagged(tmp_path):
    # PR-18: an unpaired HbmAllocator.lease() holds device-budget
    # bytes for the process lifetime — phantom pressure that evicts
    # innocent models.
    src = _source(tmp_path, """
        def leaky(allocator, build):
            lease = allocator.lease("m", "kv_pages", 1 << 20)
            build()          # raises -> the lease leaks
            allocator.release(lease)

        def never_released(hbm):
            lease = hbm.lease("m", "weights", 64)
            return lease.nbytes
    """)
    findings = check_resource_pairing(src)
    assert _ids(findings) == ["resource-pairing"] * 2
    assert "HBM lease" in findings[1].message


def test_resource_pairing_hbm_lease_clean_forms(tmp_path):
    src = _source(tmp_path, """
        def finally_paired(allocator, build):
            lease = allocator.lease("m", "kv_pages", 1 << 20)
            try:
                build(lease)
            finally:
                allocator.release(lease)

        def attribute_handoff(hbm, region):
            # Ownership parked on the owning object (the arena /
            # ensemble pattern): teardown releases it.
            region.hbm_lease = hbm.lease("arena", "regions", 64)

        def model_sweep(allocator, teardown):
            lease = allocator.lease("m", "weights", 64)
            try:
                teardown()
            finally:
                allocator.release_model("m")
    """)
    assert check_resource_pairing(src) == []


def test_resource_pairing_non_hbm_lease_not_flagged(tmp_path):
    # `lease` is a common verb — only hbm/alloc-named receivers
    # engage the pairing rule.
    src = _source(tmp_path, """
        def fine(contract):
            return contract.lease("office", months=12)
    """)
    assert check_resource_pairing(src) == []


def test_resource_pairing_pager_page_out(tmp_path):
    # A pager.page_out() whose host state is neither restored nor
    # handed off strands weights on the host with the device bytes
    # already freed.
    src = _source(tmp_path, """
        def leaky(pager):
            state = pager.page_out()
            return len(state)

        def restored_in_finally(pager, wait):
            state = pager.page_out()
            try:
                wait()
            finally:
                pager.restore(state)

        def attribute_handoff(lease):
            lease.host_state = lease.pager.page_out()

        def non_pager_receiver(editor):
            editor.page_out()
    """)
    findings = check_resource_pairing(src)
    assert _ids(findings) == ["resource-pairing"]
    assert "paged-out weight state" in findings[0].message
    assert findings[0].line == 3


def test_resource_pairing_suppressed(tmp_path):
    src = _source(tmp_path, """
        def adjacent(repo):
            # tpulint: disable=resource-pairing -- begin/finish are
            # adjacent, nothing can raise between them
            repo.begin_unload("m")
            repo.finish_unload("m")
    """)
    findings = check_resource_pairing(src)
    assert [f for f in findings
            if not src.suppressed(f.checker, f.line)] == []


# -- status-literal / retry-after -------------------------------------------

def test_status_literal_violating(tmp_path):
    src = _source(tmp_path, """
        STATUS = {"NOT_FOUND": 404, "UNAVAILABLE": 503}

        def reply(web):
            return web.json_response({}, status=503)

        def retryable(code):
            return code in (503, 429)
    """)
    checkers = _ids(check_status_literals(src))
    assert checkers == ["status-literal"] * 3


def test_status_literal_clean(tmp_path):
    src = _source(tmp_path, """
        from client_tpu import status_map

        def reply(web, error):
            status = status_map.http_status(error.status())
            return web.json_response(
                {}, status=status,
                headers=status_map.retry_after_headers(status, error))
    """)
    assert check_status_literals(src) == []


def test_status_literal_allowed_in_status_map(tmp_path):
    src = _source(tmp_path, """
        HTTP_STATUS = {"NOT_FOUND": 404, "UNAVAILABLE": 503}
    """, rel="client_tpu/status_map.py")
    assert check_status_literals(src) == []


def test_retry_after_violating_and_clean(tmp_path):
    src = _source(tmp_path, """
        from client_tpu.utils import InferenceServerException

        def bad():
            raise InferenceServerException("shed", status="UNAVAILABLE")

        def good_attach():
            error = InferenceServerException(
                "shed", status="UNAVAILABLE")
            error.retry_after_s = 0.5
            raise error

        def not_retryable_is_fine():
            raise InferenceServerException("nope", status="NOT_FOUND")
    """)
    findings = check_retry_after(src)
    assert len(findings) == 1
    assert "UNAVAILABLE" in findings[0].message
    assert findings[0].line == 5


def test_retry_after_nested_helper_attach_does_not_excuse(tmp_path):
    # A nested helper attaching retry_after_s to ITS local must not
    # excuse the enclosing function's bare construction.
    src = _source(tmp_path, """
        from client_tpu.utils import InferenceServerException

        def outer():
            def helper(make):
                error = make()
                error.retry_after_s = 1.0
                return error
            error = InferenceServerException("shed", status="UNAVAILABLE")
            raise error
    """)
    assert _ids(check_retry_after(src)) == ["retry-after"]


def test_retry_after_suppressed(tmp_path):
    # A disable on the statement's CLOSING line does not cover the
    # finding (it anchors at the statement's first line) — documented
    # placement is inline on the first line or stand-alone above.
    src = _source(tmp_path, """
        from client_tpu.utils import InferenceServerException

        def tolerated():
            raise InferenceServerException(
                "x", status="UNAVAILABLE"
            )  # tpulint: disable=retry-after -- wire-parity shim
    """)
    findings = check_retry_after(src)
    assert len(findings) == 1
    assert src.suppressed("retry-after", findings[0].line) is False
    src2 = _source(tmp_path, """
        from client_tpu.utils import InferenceServerException

        def tolerated():
            # tpulint: disable=retry-after -- wire-parity shim
            raise InferenceServerException(
                "x", status="UNAVAILABLE")
    """, rel="client_tpu/server/fixture2.py")
    findings2 = check_retry_after(src2)
    assert [f for f in findings2
            if not src2.suppressed(f.checker, f.line)] == []


# -- aio-blocking -----------------------------------------------------------

def test_aio_blocking_violating(tmp_path):
    src = _source(tmp_path, """
        import time

        async def handler():
            time.sleep(1)
    """)
    findings = check_aio_blocking(src)
    assert _ids(findings) == ["aio-blocking"]
    assert "event loop" in findings[0].message


def test_aio_blocking_clean(tmp_path):
    src = _source(tmp_path, """
        import asyncio, time

        async def handler(loop, event, fn):
            await asyncio.sleep(1)
            await event.wait()          # awaited -> non-blocking
            await loop.run_in_executor(None, fn)

        def sync_helper():
            time.sleep(1)               # sync context: fine here
    """)
    assert check_aio_blocking(src) == []


def test_aio_blocking_suppressed(tmp_path):
    src = _source(tmp_path, """
        async def handler(task):
            # tpulint: disable=aio-blocking -- task is settled,
            # result() returns immediately
            return task.result()
    """)
    findings = check_aio_blocking(src)
    assert [f for f in findings
            if not src.suppressed(f.checker, f.line)] == []


# -- drift ------------------------------------------------------------------

def test_proto_syntax_slash_comment_flagged():
    bad = "message M {\n  uint64 a = 1; / a stray slash comment\n}\n"
    findings = _proto_syntax(bad, "client_tpu/protocol/x.proto")
    assert len(findings) == 1 and "stray '/'" in findings[0].message
    assert findings[0].line == 2
    clean = ("// fine\nmessage M {\n  uint64 a = 1; // also fine\n"
             "  /* block */ uint64 b = 2;\n}\n")
    assert _proto_syntax(clean, "x.proto") == []


def test_proto_drift_detects_corrupted_proto(tmp_path):
    proto_dir = tmp_path / "client_tpu" / "protocol"
    proto_dir.mkdir(parents=True)
    for name in ("inference.proto", "model_config.proto",
                 "inference_pb2.py", "model_config_pb2.py"):
        shutil.copy(REPO / "client_tpu" / "protocol" / name,
                    proto_dir / name)
    # Injecting a '/'-comment (the PR-8 defect) must fail the gate.
    path = proto_dir / "inference.proto"
    path.write_text(path.read_text().replace(
        "syntax =", "/ stray comment\nsyntax =", 1))
    findings = check_proto_drift(tmp_path)
    assert any("stray '/'" in f.message for f in findings)
    # And removing a patched field from the .proto text must too.
    path.write_text(path.read_text().replace(
        "/ stray comment\n", "").replace("shed_count = 14;", ""))
    findings = check_proto_drift(tmp_path)
    assert any("shed_count" in f.message and "out of sync" in f.message
               for f in findings)


def test_metrics_doc_drift_both_directions(tmp_path):
    server = tmp_path / "client_tpu" / "server"
    server.mkdir(parents=True)
    (server / "core.py").write_text(textwrap.dedent("""
        def render(family):
            family("tpu_undocumented_total", "counter", "h", [])
    """))
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "metrics.md").write_text(
        "| `tpu_ghost_family` | counter | model | vanished |\n")
    findings = check_metrics_doc_drift(tmp_path)
    messages = [f.message for f in findings]
    assert any("tpu_undocumented_total" in m and "not documented" in m
               for m in messages)
    assert any("tpu_ghost_family" in m for m in messages)


# -- suppression + baseline machinery ---------------------------------------

def test_bad_suppression_reported(tmp_path):
    src = _source(tmp_path, """
        import time

        def f(lockish):
            with lockish.the_lock:
                time.sleep(1)  # tpulint: disable=lock-discipline
    """)
    assert len(src.bad_suppressions) == 1
    assert src.bad_suppressions[0].checker == "bad-suppression"
    assert "reason" in src.bad_suppressions[0].message


def test_unknown_checker_id_in_suppression(tmp_path):
    src = _source(tmp_path, """
        x = 1  # tpulint: disable=no-such-checker -- because
    """)
    assert len(src.bad_suppressions) == 1
    assert "unknown checker" in src.bad_suppressions[0].message


def test_baseline_accepts_then_goes_stale(tmp_path):
    rel = "client_tpu/server/fixture.py"
    src = _source(tmp_path, """
        import time

        class T:
            def f(self, big_lock):
                with big_lock:
                    time.sleep(1)
    """, rel=rel)
    findings = check_lock_discipline(src)
    assert len(findings) == 1
    baseline_path = tmp_path / "baseline.json"
    framework.save_baseline(findings, tmp_path, baseline_path)
    baseline = framework.load_baseline(baseline_path)
    new, accepted, stale = framework.apply_baseline(
        findings, baseline, tmp_path)
    assert new == [] and len(accepted) == 1 and stale == []
    # Shift the file by one line: the anchored text no longer matches
    # -> the finding is NEW again AND the entry is STALE.
    path = tmp_path / rel
    path.write_text("# shifted\n" + path.read_text())
    shifted = check_lock_discipline(framework.SourceFile(path, tmp_path))
    new, accepted, stale = framework.apply_baseline(
        shifted, baseline, tmp_path)
    assert len(new) == 1 and accepted == [] and len(stale) == 1
    assert "stale" in stale[0]


def test_baseline_entry_for_fixed_finding_is_stale(tmp_path):
    rel = "client_tpu/server/fixture.py"
    src = _source(tmp_path, """
        import time

        class T:
            def f(self, big_lock):
                with big_lock:
                    time.sleep(1)
    """, rel=rel)
    findings = check_lock_discipline(src)
    baseline_path = tmp_path / "baseline.json"
    framework.save_baseline(findings, tmp_path, baseline_path)
    # Fix the defect; the baseline must demand pruning (it only ever
    # shrinks — suppressions for deleted code cannot pile up).
    path = tmp_path / rel
    path.write_text(path.read_text().replace(
        "time.sleep(1)", "pass"))
    clean = check_lock_discipline(framework.SourceFile(path, tmp_path))
    new, accepted, stale = framework.apply_baseline(
        clean, framework.load_baseline(baseline_path), tmp_path)
    assert new == [] and accepted == [] and len(stale) == 1


def test_update_baseline_refuses_bad_suppressions(tmp_path):
    _source(tmp_path, """
        import time

        class T:
            def f(self, big_lock):
                with big_lock:
                    time.sleep(1)  # tpulint: disable=lock-discipline
    """, rel="client_tpu/server/fixture.py")
    baseline_path = tmp_path / "baseline.json"
    tpulint.update_baseline(tmp_path, baseline_path)
    entries = framework.load_baseline(baseline_path)
    assert entries  # the (unsuppressed) lock finding IS baselined
    assert all(e["checker"] != "bad-suppression" for e in entries)
    # ...so the reason-less disable still fails the gate.
    new, _accepted, _stale = framework.apply_baseline(
        tpulint.run(tmp_path), entries, tmp_path)
    assert any(f.checker == "bad-suppression" for f in new)


# -- acceptance-criteria injections -----------------------------------------

@pytest.mark.parametrize("snippet,checker", [
    ("""
     import threading, time

     class T:
         def __init__(self):
             self._lock = threading.Lock()

         def f(self):
             with self._lock:
                 time.sleep(0.5)
     """, "lock-discipline"),
    ("""
     def f(quotas, work):
         token = quotas.acquire("tenant")
         work()
         quotas.release(token)
     """, "resource-pairing"),
    ("""
     def f(web):
         return web.json_response({}, status=503)
     """, "status-literal"),
])
def test_injected_defect_fails_gate(tmp_path, snippet, checker):
    """The ISSUE acceptance criteria verbatim: a lock-held time.sleep,
    an unpaired tenant acquire, and a bare 503 literal each produce a
    path:line diagnostic that the (empty-for-that-file) baseline does
    not absorb."""
    _source(tmp_path, snippet, rel="client_tpu/server/injected.py")
    findings = tpulint.run(tmp_path)
    hits = [f for f in findings if f.checker == checker
            and f.path == "client_tpu/server/injected.py"]
    assert hits, findings
    assert hits[0].line > 0
    assert "client_tpu/server/injected.py:%d" % hits[0].line \
        in hits[0].format()
    new, _accepted, _stale = framework.apply_baseline(
        hits, framework.load_baseline(tmp_path / "nope.json"), tmp_path)
    assert new == hits  # nothing absorbs them -> the gate fails


# -- end-to-end over the real tree ------------------------------------------

def test_real_tree_zero_nonbaselined_findings():
    """The CI gate's exact contract: the shipped tree + shipped
    baseline produce zero new findings and zero stale entries."""
    new, accepted, stale = tpulint.run_gated()
    assert new == [], "\n".join(f.format() for f in new)
    assert stale == [], "\n".join(stale)
    # The shipped baseline is empty — the checkers' findings were
    # FIXED in this PR, not baselined. Keep it that way.
    assert accepted == []


def test_checker_catalog_matches_framework():
    for checker_id in ("lock-discipline", "lock-order",
                       "resource-pairing", "status-literal",
                       "retry-after", "aio-blocking", "proto-drift",
                       "metrics-doc-drift", "bad-suppression"):
        assert checker_id in framework.CHECKER_IDS
