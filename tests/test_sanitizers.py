"""Race detection: builds the native tree with ThreadSanitizer and
runs the most threading-heavy test binaries under it (SURVEY.md §5 —
the reference configures no sanitizer jobs; the load managers,
async clients, and channel cache here are all lock-based concurrent
code, exactly what TSAN exists for)."""

import os
import pathlib
import shutil
import subprocess

import pytest

pytestmark = pytest.mark.slow  # TSAN cmake build tree (~3 min)

REPO = pathlib.Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"
TSAN_BUILD = NATIVE / "build-tsan"


@pytest.fixture(scope="module")
def tsan_build():
    if shutil.which("cmake") is None or shutil.which("ninja") is None:
        pytest.skip("cmake/ninja not available")
    if not (TSAN_BUILD / "build.ninja").exists():
        proc = subprocess.run(
            ["cmake", "-S", str(NATIVE), "-B", str(TSAN_BUILD),
             "-G", "Ninja", "-DTPUCLIENT_SANITIZE=thread",
             # The CPython-embedding backend is out of scope for TSAN
             # (the interpreter itself is not TSAN-instrumented).
             "-DCMAKE_DISABLE_FIND_PACKAGE_Python3=ON"],
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
    proc = subprocess.run(
        ["ninja", "-C", str(TSAN_BUILD), "test_core", "test_perf_harness",
         "test_grpc_client", "test_h2_server"],
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, (proc.stdout[-2000:] + proc.stderr[-2000:])
    return TSAN_BUILD


@pytest.mark.parametrize(
    "binary", ["test_core", "test_perf_harness", "test_grpc_client",
               "test_h2_server"])
def test_tsan_clean(tsan_build, binary):
    """halt_on_error turns any detected data race into a non-zero
    exit; these binaries exercise the load managers' worker pools,
    the mock backend's detached callback threads, and the async
    client paths."""
    proc = subprocess.run(
        [str(tsan_build / binary)],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, TSAN_OPTIONS="halt_on_error=1"),
    )
    assert "WARNING: ThreadSanitizer" not in proc.stdout + proc.stderr, (
        proc.stdout[-3000:] + proc.stderr[-3000:]
    )
    assert proc.returncode == 0, (
        proc.stdout[-3000:] + proc.stderr[-3000:]
    )
