"""Race detection: builds the native tree with ThreadSanitizer and
runs the most threading-heavy test binaries under it (SURVEY.md §5 —
the reference configures no sanitizer jobs; the load managers,
async clients, and channel cache here are all lock-based concurrent
code, exactly what TSAN exists for).

Split per docs/static_analysis.md: the cheap "the TSAN build tree
CONFIGURES" check runs in tier-1 (a CMakeLists/toolchain regression
fails fast, every run), while the full instrumented build + binary
runs stay ``slow`` (~3 min build). The Python-side concurrency gets
its static coverage from ``python -m tools.tpulint`` (lock-discipline
/ lock-order / resource-pairing) — TSAN covers the native side
dynamically."""

import os
import pathlib
import shutil
import subprocess

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"
TSAN_BUILD = NATIVE / "build-tsan"

_CMAKE_ARGS = [
    "-G", "Ninja", "-DTPUCLIENT_SANITIZE=thread",
    # The CPython-embedding backend is out of scope for TSAN
    # (the interpreter itself is not TSAN-instrumented).
    "-DCMAKE_DISABLE_FIND_PACKAGE_Python3=ON",
]


def _configure(build_dir: pathlib.Path) -> "subprocess.CompletedProcess":
    return subprocess.run(
        ["cmake", "-S", str(NATIVE), "-B", str(build_dir)] + _CMAKE_ARGS,
        capture_output=True, text=True, timeout=300,
    )


def test_tsan_tree_configures(tmp_path):
    """Tier-1 (not slow): the TSAN configuration itself must stay
    valid — a -DTPUCLIENT_SANITIZE=thread configure that errors means
    the slow job can never run, and that regression should fail in
    every CI run, not only when someone remembers -m slow.

    Reuses the persistent build tree when it exists (incremental
    re-configure is ~1s); otherwise configures into tmp_path so
    tier-1 leaves no build tree behind."""
    if shutil.which("cmake") is None or shutil.which("ninja") is None:
        pytest.skip("cmake/ninja not available")
    build_dir = TSAN_BUILD if (TSAN_BUILD / "build.ninja").exists() \
        else tmp_path / "build-tsan"
    proc = _configure(build_dir)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert (build_dir / "build.ninja").exists()


@pytest.fixture(scope="module")
def tsan_build():
    if shutil.which("cmake") is None or shutil.which("ninja") is None:
        pytest.skip("cmake/ninja not available")
    if not (TSAN_BUILD / "build.ninja").exists():
        proc = _configure(TSAN_BUILD)
        assert proc.returncode == 0, proc.stderr[-2000:]
    proc = subprocess.run(
        ["ninja", "-C", str(TSAN_BUILD), "test_core", "test_perf_harness",
         "test_grpc_client", "test_h2_server"],
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, (proc.stdout[-2000:] + proc.stderr[-2000:])
    return TSAN_BUILD


@pytest.mark.slow  # full TSAN cmake build tree (~3 min) + binary runs
@pytest.mark.parametrize(
    "binary", ["test_core", "test_perf_harness", "test_grpc_client",
               "test_h2_server"])
def test_tsan_clean(tsan_build, binary):
    """halt_on_error turns any detected data race into a non-zero
    exit; these binaries exercise the load managers' worker pools,
    the mock backend's detached callback threads, and the async
    client paths."""
    proc = subprocess.run(
        [str(tsan_build / binary)],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, TSAN_OPTIONS="halt_on_error=1"),
    )
    assert "WARNING: ThreadSanitizer" not in proc.stdout + proc.stderr, (
        proc.stdout[-3000:] + proc.stderr[-3000:]
    )
    assert proc.returncode == 0, (
        proc.stdout[-3000:] + proc.stderr[-3000:]
    )
