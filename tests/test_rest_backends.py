"""TorchServe + TF-Serving backends, Python and native harness
(parity: reference client_backend/torchserve/ and
tensorflow_serving/ — mock-served, like the reference's unit tier)."""

import json
import pathlib
import subprocess
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from client_tpu._infer_common import InferInput
from client_tpu.perf.client_backend import (
    BackendKind,
    ClientBackendFactory,
)

REPO = pathlib.Path(__file__).resolve().parents[1]


class _RestHandler(BaseHTTPRequestHandler):
    """Mock TorchServe (/predictions/<m>) + TF-Serving REST
    (/v1/models/<m>:predict, .../metadata) endpoints."""

    def log_message(self, *args):
        pass

    def _reply(self, payload: dict, status: int = 200):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path.endswith("/metadata"):
            self._reply({
                "model_spec": {"name": "m"},
                "metadata": {"signature_def": {"signature_def": {
                    "serving_default": {
                        "inputs": {"x": {
                            "dtype": "DT_FLOAT",
                            "tensor_shape": {"dim": [{"size": "-1"},
                                                     {"size": "4"}]},
                        }},
                        "outputs": {"y": {"dtype": "DT_FLOAT"}},
                    },
                }}},
            })
        else:
            self._reply({"error": "not found"}, 404)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        self.server.requests.append((self.path, body))
        if self.path.startswith("/predictions/"):
            self._reply({"prediction": body.decode(errors="replace")})
        elif self.path.endswith(":predict"):
            doc = json.loads(body)
            inputs = doc.get("inputs", {})
            def summarize(v):
                try:
                    return [float(np.asarray(v, dtype=np.float64).sum())]
                except (ValueError, TypeError):
                    return v  # string tensors echo back

            outputs = {name: summarize(v) for name, v in inputs.items()}
            self._reply({"outputs": outputs})
        else:
            self._reply({"error": "bad path"}, 404)


@pytest.fixture(scope="module")
def rest_server():
    server = ThreadingHTTPServer(("127.0.0.1", 0), _RestHandler)
    server.requests = []
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield server
    server.shutdown()


def _url(server):
    return "127.0.0.1:%d" % server.server_address[1]


def test_torchserve_backend_infer(rest_server):
    backend = ClientBackendFactory(
        BackendKind.TORCHSERVE, url=_url(rest_server)).create()
    meta = backend.model_metadata("squeezenet")
    assert meta["inputs"][0]["datatype"] == "BYTES"
    data = InferInput("data", [1], "BYTES")
    data.set_data_from_numpy(np.array([b"image-bytes"], dtype=np.object_))
    result = backend.infer("squeezenet", [data])
    doc = result.as_json()
    assert doc["prediction"] == "image-bytes"
    assert result.get_parameters()["triton_final_response"] is True


def test_torchserve_backend_async(rest_server):
    backend = ClientBackendFactory(
        BackendKind.TORCHSERVE, url=_url(rest_server)).create()
    data = InferInput("data", [1], "BYTES")
    data.set_data_from_numpy(np.array([b"x"], dtype=np.object_))
    done = threading.Event()
    holder = {}

    def callback(result, error):
        holder["result"], holder["error"] = result, error
        done.set()

    backend.async_infer(callback, "m", [data])
    assert done.wait(10)
    assert holder["error"] is None
    assert holder["result"].as_json()["prediction"] == "x"


def test_tfserving_backend_metadata_and_infer(rest_server):
    # tfserving_grpc=False exercises the REST predict API variant
    backend = ClientBackendFactory(
        BackendKind.TFSERVING, url=_url(rest_server),
        tfserving_grpc=False).create()
    meta = backend.model_metadata("m")
    assert meta["platform"] == "tensorflow_serving"
    assert meta["inputs"][0]["name"] == "x"
    assert meta["inputs"][0]["datatype"] == "FP32"
    assert meta["inputs"][0]["shape"] == [-1, 4]

    x = InferInput("x", [2, 2], "FP32")
    x.set_data_from_numpy(np.array([[1, 2], [3, 4]], dtype=np.float32))
    result = backend.infer("m", [x])
    assert result.as_json()["outputs"]["x"] == [10.0]


def test_tfserving_backend_bytes_input(rest_server):
    backend = ClientBackendFactory(
        BackendKind.TFSERVING, url=_url(rest_server),
        tfserving_grpc=False).create()
    s = InferInput("s", [2], "BYTES")
    s.set_data_from_numpy(np.array([b"a", b"b"], dtype=np.object_))
    result = backend.infer("m", [s])
    assert result.as_json()["outputs"]["s"] == ["a", "b"]


def test_rest_backends_reject_streaming(rest_server):
    from client_tpu.utils import InferenceServerException

    for kind in (BackendKind.TORCHSERVE, BackendKind.TFSERVING):
        backend = ClientBackendFactory(kind, url=_url(rest_server),
                                       tfserving_grpc=False).create()
        with pytest.raises(InferenceServerException):
            backend.async_stream_infer("m", [])


@pytest.mark.parametrize("service_kind", ["torchserve", "tfserving"])
def test_native_perf_analyzer_rest_e2e(rest_server, tmp_path, service_kind):
    """Native harness end-to-end against the mock REST endpoints."""
    binary = REPO / "native" / "build" / "perf_analyzer"
    if not binary.exists():
        pytest.skip("native perf_analyzer not built")
    input_file = tmp_path / "input.json"
    if service_kind == "tfserving":
        # The native backend fetches the signature from the mock's
        # /metadata endpoint: one FP32 input named "x" of shape [-1,4].
        step = {"x": {"content": [1.0, 2.0, 3.0, 4.0], "shape": [1, 4]}}
    else:
        step = {"data": ["payload"]}
    input_file.write_text(json.dumps({"data": [step]}))
    csv = tmp_path / "latency.csv"
    proc = subprocess.run(
        [str(binary), "-m", "anymodel", "-u", _url(rest_server),
         "--service-kind", service_kind, "-i", "http",
         "--input-data", str(input_file),
         "--concurrency-range", "2", "-p", "400", "-r", "3", "-s", "90",
         "-f", str(csv)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = csv.read_text().strip().splitlines()
    assert len(rows) >= 2
    throughput = float(rows[1].split(",")[1])
    assert throughput > 0
