"""TPU shared-memory tests — the north-star path (SURVEY.md §3.5):
region lifecycle, zero-copy inference I/O, DLPack ingestion, both
remote (arena service over gRPC) and in-process (co-located) modes."""

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
import client_tpu.utils.tpu_shared_memory as tpushm
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.tpu_arena import TpuArena
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def server():
    core = build_core(["add_sub_fp32"])
    assert core.memory.arena is not None, "arena must be available"
    handle = start_grpc_server(core=core)
    yield handle
    handle.stop()


@pytest.fixture()
def remote_arena(server):
    tpushm.set_arena_endpoint(server.address)
    yield
    tpushm.reset_arena_endpoint()


@pytest.fixture()
def client(server):
    with grpcclient.InferenceServerClient(server.address) as c:
        yield c


def test_region_lifecycle(remote_arena):
    handle = tpushm.create_shared_memory_region("r0", 64, 0)
    assert "r0" in tpushm.allocated_shared_memory_regions()
    raw = tpushm.get_raw_handle(handle)
    assert b"region_id" in raw
    tpushm.destroy_shared_memory_region(handle)
    assert "r0" not in tpushm.allocated_shared_memory_regions()


def test_set_get_roundtrip(remote_arena):
    x = np.random.rand(4, 4).astype(np.float32)
    handle = tpushm.create_shared_memory_region("rt", x.nbytes, 0)
    try:
        tpushm.set_shared_memory_region(handle, [x])
        out = tpushm.get_contents_as_numpy(handle, "FP32", [4, 4])
        np.testing.assert_array_equal(out, x)
    finally:
        tpushm.destroy_shared_memory_region(handle)


def test_bytes_roundtrip(remote_arena):
    arr = np.array([b"alpha", b"bravo!"], dtype=np.object_)
    handle = tpushm.create_shared_memory_region("bt", 64, 0)
    try:
        tpushm.set_shared_memory_region(handle, [arr])
        out = tpushm.get_contents_as_numpy(handle, "BYTES", [2])
        assert out.tolist() == arr.tolist()
    finally:
        tpushm.destroy_shared_memory_region(handle)


def test_zero_copy_infer(remote_arena, client):
    """The full north-star flow: create regions, register, infer with
    device-resident I/O, read results (reference §3.5 call stack)."""
    x = np.random.rand(16).astype(np.float32)
    y = np.random.rand(16).astype(np.float32)
    byte_size = x.nbytes
    h_in0 = tpushm.create_shared_memory_region("t_in0", byte_size, 0)
    h_in1 = tpushm.create_shared_memory_region("t_in1", byte_size, 0)
    h_out0 = tpushm.create_shared_memory_region("t_out0", byte_size, 0)
    try:
        tpushm.set_shared_memory_region(h_in0, [x])
        tpushm.set_shared_memory_region(h_in1, [y])
        client.register_tpu_shared_memory(
            "t_in0", tpushm.get_raw_handle(h_in0), 0, byte_size
        )
        client.register_tpu_shared_memory(
            "t_in1", tpushm.get_raw_handle(h_in1), 0, byte_size
        )
        client.register_tpu_shared_memory(
            "t_out0", tpushm.get_raw_handle(h_out0), 0, byte_size
        )
        status = client.get_tpu_shared_memory_status()
        assert set(status.regions.keys()) == {"t_in0", "t_in1", "t_out0"}

        inputs = [
            grpcclient.InferInput("INPUT0", [16], "FP32"),
            grpcclient.InferInput("INPUT1", [16], "FP32"),
        ]
        inputs[0].set_shared_memory("t_in0", byte_size)
        inputs[1].set_shared_memory("t_in1", byte_size)
        outputs = [
            grpcclient.InferRequestedOutput("OUTPUT0"),
            grpcclient.InferRequestedOutput("OUTPUT1"),
        ]
        outputs[0].set_shared_memory("t_out0", byte_size)
        result = client.infer("add_sub_fp32", inputs, outputs=outputs)

        assert result.as_numpy("OUTPUT0") is None  # lives in HBM
        out0 = tpushm.get_contents_as_numpy(h_out0, "FP32", [16])
        np.testing.assert_allclose(out0, x + y, rtol=1e-6)
        np.testing.assert_allclose(result.as_numpy("OUTPUT1"), x - y,
                                   rtol=1e-6)
    finally:
        client.unregister_tpu_shared_memory()
        for h in (h_in0, h_in1, h_out0):
            tpushm.destroy_shared_memory_region(h)


def test_register_bogus_handle(remote_arena, client):
    with pytest.raises(InferenceServerException) as exc:
        client.register_tpu_shared_memory("bogus", b"not-a-handle", 0, 64)
    assert exc.value.status() == "INVALID_ARGUMENT"


def test_register_wrong_size(remote_arena, client):
    handle = tpushm.create_shared_memory_region("sz", 64, 0)
    try:
        with pytest.raises(InferenceServerException) as exc:
            client.register_tpu_shared_memory(
                "sz", tpushm.get_raw_handle(handle), 0, 128
            )
        assert exc.value.status() == "INVALID_ARGUMENT"
    finally:
        tpushm.destroy_shared_memory_region(handle)


def test_in_process_zero_copy():
    """Co-located mode: jax.Array in, identity-preserved device array
    out — the true zero-copy contract."""
    import jax
    import jax.numpy as jnp

    arena = TpuArena()
    tpushm.set_arena(arena)
    try:
        x = jnp.arange(16, dtype=jnp.float32)
        handle = tpushm.create_shared_memory_region("ip", x.nbytes, 0)
        tpushm.set_shared_memory_region_from_dlpack(handle, x)
        tensor = tpushm.as_shared_memory_tensor(handle, "FP32", [16])
        # zero copy: the very same jax.Array object is handed back
        assert tensor.array is x
        # and it is DLPack-capable
        reread = np.from_dlpack(tensor)
        np.testing.assert_array_equal(reread, np.arange(16, dtype=np.float32))
        tpushm.destroy_shared_memory_region(handle)
    finally:
        tpushm.reset_arena_endpoint()


def test_in_process_torch_dlpack():
    import torch

    arena = TpuArena()
    tpushm.set_arena(arena)
    try:
        t = torch.arange(8, dtype=torch.float32)
        handle = tpushm.create_shared_memory_region("tt", 32, 0)
        tpushm.set_shared_memory_region_from_dlpack(handle, t)
        out = tpushm.get_contents_as_numpy(handle, "FP32", [8])
        np.testing.assert_array_equal(out, t.numpy())
        tpushm.destroy_shared_memory_region(handle)
    finally:
        tpushm.reset_arena_endpoint()


def test_typed_view_from_raw_write():
    """Writes without dtype metadata still resolve to typed device
    arrays via on-device bitcast."""
    arena = TpuArena()
    tpushm.set_arena(arena)
    try:
        a = np.arange(8, dtype=np.int32)
        b = np.arange(8, 16, dtype=np.int32)
        handle = tpushm.create_shared_memory_region("2arr", 64, 0)
        tpushm.set_shared_memory_region(handle, [a, b])  # raw path
        out = tpushm.get_contents_as_numpy(handle, "INT32", [16])
        np.testing.assert_array_equal(out[:8], a)
        np.testing.assert_array_equal(out[8:], b)
        tensor = tpushm.as_shared_memory_tensor(handle, "INT32", [16])
        np.testing.assert_array_equal(np.asarray(tensor.array)[:8], a)
        tpushm.destroy_shared_memory_region(handle)
    finally:
        tpushm.reset_arena_endpoint()


class TestSegmentedArena:
    """Segment data plane: typed multi-tensor layouts, no whole-region
    round-trips on partial writes (VERDICT r1 weak #4)."""

    def test_multi_tensor_write_keeps_dtype(self):
        arena = TpuArena()
        handle = arena.create_region(4096)
        import json as _json

        region_id = _json.loads(handle)["region_id"]
        a = np.arange(8, dtype=np.float32)
        b = np.arange(6, dtype=np.int64).reshape(2, 3)
        arena.write(region_id, 0, a.tobytes(), "FP32", [8])
        arena.write(region_id, 256, b.tobytes(), "INT64", [2, 3])
        # Both tensors resolve typed, at their own offsets.
        got_a = np.asarray(arena.as_typed_array(region_id, 0, 32,
                                                "FP32", [8]))
        got_b = np.asarray(arena.as_typed_array(region_id, 256, 48,
                                                "INT64", [2, 3]))
        np.testing.assert_array_equal(got_a, a)
        np.testing.assert_array_equal(got_b, b)

    def test_partial_write_no_full_region_readback(self, monkeypatch):
        """Writing tensor B must not serialize tensor A's segment
        (the old path pulled the whole region to host per write)."""
        arena = TpuArena()
        handle = arena.create_region(1 << 20)
        import json as _json

        region_id = _json.loads(handle)["region_id"]
        a = np.ones(1024, dtype=np.float32)
        arena.write(region_id, 0, a.tobytes(), "FP32", [1024])

        calls = []
        original = TpuArena._segment_view

        def spy(segment):
            calls.append(segment.offset)
            return original(segment)

        monkeypatch.setattr(TpuArena, "_segment_view",
                            staticmethod(spy))
        # Disjoint write: no segment serialization at all.
        b = np.zeros(512, dtype=np.int32)
        arena.write(region_id, 8192, b.tobytes(), "INT32", [512])
        assert calls == [], "disjoint write read back existing segments"
        # A's device array is the very same object (never re-staged).
        seg_a = arena._get(region_id).segments[0]
        got = arena.as_typed_array(region_id, 0, 4096, "FP32", [1024])
        assert got is seg_a.array

    def test_store_at_offset_is_reference_swap(self):
        arena = TpuArena()
        handle = arena.create_region(65536)
        import json as _json

        region_id = _json.loads(handle)["region_id"]
        import jax.numpy as jnp

        value = jnp.arange(16, dtype=jnp.float32)
        arena.store(region_id, 1024, 64, value)
        got = arena.as_typed_array(region_id, 1024, 64, "FP32", [16])
        assert got is value  # by-reference, even at a non-zero offset

    def test_overlap_carves_only_touched_segment(self):
        arena = TpuArena()
        handle = arena.create_region(4096)
        import json as _json

        region_id = _json.loads(handle)["region_id"]
        a = np.arange(16, dtype=np.float32)          # bytes [0, 64)
        b = np.arange(16, dtype=np.float32) + 100    # bytes [128, 192)
        arena.write(region_id, 0, a.tobytes(), "FP32", [16])
        arena.write(region_id, 128, b.tobytes(), "FP32", [16])
        # Overwrite the middle of A only.
        patch = np.full(4, -1.0, dtype=np.float32)
        arena.write(region_id, 16, patch.tobytes())
        # A's head/tail survive; B is untouched and still typed.
        raw = arena.read(region_id, 0, 64)
        merged = np.frombuffer(raw, np.float32)
        expected = a.copy()
        expected[4:8] = -1.0
        np.testing.assert_array_equal(merged, expected)
        got_b = arena.as_typed_array(region_id, 128, 64, "FP32", [16])
        np.testing.assert_array_equal(np.asarray(got_b), b)

    def test_read_spanning_segments_zero_fills_gaps(self):
        arena = TpuArena()
        handle = arena.create_region(1024)
        import json as _json

        region_id = _json.loads(handle)["region_id"]
        arena.write(region_id, 0, b"\x01\x02", "", None)
        arena.write(region_id, 6, b"\x03\x04", "", None)
        assert arena.read(region_id, 0, 8) == \
            b"\x01\x02\x00\x00\x00\x00\x03\x04"

    def test_smaller_bytes_restore_no_stale_tail(self):
        """Re-storing a smaller BYTES tensor leaves no stale framing
        bytes for read-to-end."""
        arena = TpuArena()
        handle = arena.create_region(4096)
        import json as _json

        region_id = _json.loads(handle)["region_id"]
        big = np.array([b"a" * 80], dtype=np.object_)
        small = np.array([b"b" * 30], dtype=np.object_)
        arena.store(region_id, 0, 4096, big)
        arena.store(region_id, 0, 4096, small)
        from client_tpu.utils import deserialize_bytes_tensor

        data = arena.read(region_id, 0, 0)
        out = deserialize_bytes_tensor(data)
        assert list(out) == [b"b" * 30]

    def test_numeric_view_over_bytes_rejected(self):
        arena = TpuArena()
        handle = arena.create_region(1024)
        import json as _json

        region_id = _json.loads(handle)["region_id"]
        arr = np.array([b"hello"], dtype=np.object_)
        arena.store(region_id, 0, 1024, arr)
        with pytest.raises(InferenceServerException):
            arena.as_typed_array(region_id, 0, 8, "FP32", [2])
