"""TPU shared-memory tests — the north-star path (SURVEY.md §3.5):
region lifecycle, zero-copy inference I/O, DLPack ingestion, both
remote (arena service over gRPC) and in-process (co-located) modes."""

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
import client_tpu.utils.tpu_shared_memory as tpushm
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.tpu_arena import TpuArena
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def server():
    core = build_core(["add_sub_fp32"])
    assert core.memory.arena is not None, "arena must be available"
    handle = start_grpc_server(core=core)
    yield handle
    handle.stop()


@pytest.fixture()
def remote_arena(server):
    tpushm.set_arena_endpoint(server.address)
    yield
    tpushm._default_transport = None


@pytest.fixture()
def client(server):
    with grpcclient.InferenceServerClient(server.address) as c:
        yield c


def test_region_lifecycle(remote_arena):
    handle = tpushm.create_shared_memory_region("r0", 64, 0)
    assert "r0" in tpushm.allocated_shared_memory_regions()
    raw = tpushm.get_raw_handle(handle)
    assert b"region_id" in raw
    tpushm.destroy_shared_memory_region(handle)
    assert "r0" not in tpushm.allocated_shared_memory_regions()


def test_set_get_roundtrip(remote_arena):
    x = np.random.rand(4, 4).astype(np.float32)
    handle = tpushm.create_shared_memory_region("rt", x.nbytes, 0)
    try:
        tpushm.set_shared_memory_region(handle, [x])
        out = tpushm.get_contents_as_numpy(handle, "FP32", [4, 4])
        np.testing.assert_array_equal(out, x)
    finally:
        tpushm.destroy_shared_memory_region(handle)


def test_bytes_roundtrip(remote_arena):
    arr = np.array([b"alpha", b"bravo!"], dtype=np.object_)
    handle = tpushm.create_shared_memory_region("bt", 64, 0)
    try:
        tpushm.set_shared_memory_region(handle, [arr])
        out = tpushm.get_contents_as_numpy(handle, "BYTES", [2])
        assert out.tolist() == arr.tolist()
    finally:
        tpushm.destroy_shared_memory_region(handle)


def test_zero_copy_infer(remote_arena, client):
    """The full north-star flow: create regions, register, infer with
    device-resident I/O, read results (reference §3.5 call stack)."""
    x = np.random.rand(16).astype(np.float32)
    y = np.random.rand(16).astype(np.float32)
    byte_size = x.nbytes
    h_in0 = tpushm.create_shared_memory_region("t_in0", byte_size, 0)
    h_in1 = tpushm.create_shared_memory_region("t_in1", byte_size, 0)
    h_out0 = tpushm.create_shared_memory_region("t_out0", byte_size, 0)
    try:
        tpushm.set_shared_memory_region(h_in0, [x])
        tpushm.set_shared_memory_region(h_in1, [y])
        client.register_tpu_shared_memory(
            "t_in0", tpushm.get_raw_handle(h_in0), 0, byte_size
        )
        client.register_tpu_shared_memory(
            "t_in1", tpushm.get_raw_handle(h_in1), 0, byte_size
        )
        client.register_tpu_shared_memory(
            "t_out0", tpushm.get_raw_handle(h_out0), 0, byte_size
        )
        status = client.get_tpu_shared_memory_status()
        assert set(status.regions.keys()) == {"t_in0", "t_in1", "t_out0"}

        inputs = [
            grpcclient.InferInput("INPUT0", [16], "FP32"),
            grpcclient.InferInput("INPUT1", [16], "FP32"),
        ]
        inputs[0].set_shared_memory("t_in0", byte_size)
        inputs[1].set_shared_memory("t_in1", byte_size)
        outputs = [
            grpcclient.InferRequestedOutput("OUTPUT0"),
            grpcclient.InferRequestedOutput("OUTPUT1"),
        ]
        outputs[0].set_shared_memory("t_out0", byte_size)
        result = client.infer("add_sub_fp32", inputs, outputs=outputs)

        assert result.as_numpy("OUTPUT0") is None  # lives in HBM
        out0 = tpushm.get_contents_as_numpy(h_out0, "FP32", [16])
        np.testing.assert_allclose(out0, x + y, rtol=1e-6)
        np.testing.assert_allclose(result.as_numpy("OUTPUT1"), x - y,
                                   rtol=1e-6)
    finally:
        client.unregister_tpu_shared_memory()
        for h in (h_in0, h_in1, h_out0):
            tpushm.destroy_shared_memory_region(h)


def test_register_bogus_handle(remote_arena, client):
    with pytest.raises(InferenceServerException) as exc:
        client.register_tpu_shared_memory("bogus", b"not-a-handle", 0, 64)
    assert exc.value.status() == "INVALID_ARGUMENT"


def test_register_wrong_size(remote_arena, client):
    handle = tpushm.create_shared_memory_region("sz", 64, 0)
    try:
        with pytest.raises(InferenceServerException) as exc:
            client.register_tpu_shared_memory(
                "sz", tpushm.get_raw_handle(handle), 0, 128
            )
        assert exc.value.status() == "INVALID_ARGUMENT"
    finally:
        tpushm.destroy_shared_memory_region(handle)


def test_in_process_zero_copy():
    """Co-located mode: jax.Array in, identity-preserved device array
    out — the true zero-copy contract."""
    import jax
    import jax.numpy as jnp

    arena = TpuArena()
    tpushm.set_arena(arena)
    try:
        x = jnp.arange(16, dtype=jnp.float32)
        handle = tpushm.create_shared_memory_region("ip", x.nbytes, 0)
        tpushm.set_shared_memory_region_from_dlpack(handle, x)
        tensor = tpushm.as_shared_memory_tensor(handle, "FP32", [16])
        # zero copy: the very same jax.Array object is handed back
        assert tensor.array is x
        # and it is DLPack-capable
        reread = np.from_dlpack(tensor)
        np.testing.assert_array_equal(reread, np.arange(16, dtype=np.float32))
        tpushm.destroy_shared_memory_region(handle)
    finally:
        tpushm._default_transport = None


def test_in_process_torch_dlpack():
    import torch

    arena = TpuArena()
    tpushm.set_arena(arena)
    try:
        t = torch.arange(8, dtype=torch.float32)
        handle = tpushm.create_shared_memory_region("tt", 32, 0)
        tpushm.set_shared_memory_region_from_dlpack(handle, t)
        out = tpushm.get_contents_as_numpy(handle, "FP32", [8])
        np.testing.assert_array_equal(out, t.numpy())
        tpushm.destroy_shared_memory_region(handle)
    finally:
        tpushm._default_transport = None


def test_typed_view_from_raw_write():
    """Writes without dtype metadata still resolve to typed device
    arrays via on-device bitcast."""
    arena = TpuArena()
    tpushm.set_arena(arena)
    try:
        a = np.arange(8, dtype=np.int32)
        b = np.arange(8, 16, dtype=np.int32)
        handle = tpushm.create_shared_memory_region("2arr", 64, 0)
        tpushm.set_shared_memory_region(handle, [a, b])  # raw path
        out = tpushm.get_contents_as_numpy(handle, "INT32", [16])
        np.testing.assert_array_equal(out[:8], a)
        np.testing.assert_array_equal(out[8:], b)
        tensor = tpushm.as_shared_memory_tensor(handle, "INT32", [16])
        np.testing.assert_array_equal(np.asarray(tensor.array)[:8], a)
        tpushm.destroy_shared_memory_region(handle)
    finally:
        tpushm._default_transport = None
