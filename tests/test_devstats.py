"""Device-axis observability (PR 15): HBM ledger register/release
pairing across model load/unload, replica re-init and KV
crash-rebuild; busy-time monotonicity under concurrent fused
executions; compile-counter increments on a forced shape-bucket miss;
the recompile-storm incident stamp; the /v2/debug/profile endpoint
over all three transports (single-flight, bounded duration, fallback
arm); and the /v2/debug ``devices`` section's cardinality lint."""

import json
import os
import sys
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from client_tpu._infer_common import InferInput
from client_tpu.grpc._utils import get_inference_request
from client_tpu.server import devstats as devstats_mod
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.devstats import (
    DeviceLedger,
    DeviceStats,
    MAX_LEDGER_COMPONENTS,
    OVERFLOW_ROW,
    model_array_bytes,
)
from client_tpu.server.http_embed import http_call
from client_tpu.server.http_server import start_http_server_thread
from client_tpu.server.model import ServedModel, TensorSpec

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
from metrics_lint import lint_debug_snapshot  # noqa: E402


@pytest.fixture(autouse=True)
def _stub_jax_profiler(monkeypatch):
    """The first jax-profiler start in a process imports heavy deps
    (tensorflow, ~10s) on a background thread, and an import left
    mid-flight at interpreter exit can segfault the teardown. Tests
    stub the start so the capture always takes its span-derived arm —
    which is the logic under test here; the real jax arm is exercised
    end-to-end by tools/devstats_smoke.py (which hard-exits past the
    teardown hazard)."""

    def unsupported(*_args, **_kwargs):
        raise RuntimeError("stubbed in tests")

    monkeypatch.setattr(jax.profiler, "start_trace", unsupported)
    profiler = devstats_mod.get().profiler
    before = profiler.jax_start_timeout_s
    profiler.jax_start_timeout_s = 2.0
    yield
    profiler.jax_start_timeout_s = before


def _simple_request(model_name: str, shape=(16,), batch: int = 0,
                    seed: int = 0):
    full = ([batch] + list(shape)) if batch else list(shape)
    a = np.full(full, seed % 97, dtype=np.int32)
    b = np.arange(int(np.prod(full)), dtype=np.int32).reshape(full)
    t0 = InferInput("INPUT0", full, "INT32")
    t0.set_data_from_numpy(a)
    t1 = InferInput("INPUT1", full, "INT32")
    t1.set_data_from_numpy(b)
    return get_inference_request(model_name=model_name,
                                 inputs=[t0, t1], outputs=None)


class _ArrayModel(ServedModel):
    """Add/sub with a device-resident weight array, so the ledger's
    exact-nbytes measurement has something real to count."""

    def __init__(self, name: str = "array_model", weights_n: int = 1024):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("INPUT0", "INT32", [16]),
                       TensorSpec("INPUT1", "INT32", [16])]
        self.outputs = [TensorSpec("OUTPUT0", "INT32", [16]),
                        TensorSpec("OUTPUT1", "INT32", [16])]
        self._weights = jnp.zeros((weights_n,), dtype=jnp.float32)

    def infer(self, inputs, parameters=None):
        a, b = inputs["INPUT0"], inputs["INPUT1"]
        return {"OUTPUT0": np.asarray(a) + np.asarray(b),
                "OUTPUT1": np.asarray(a) - np.asarray(b)}


# -- ledger unit semantics -------------------------------------------------


def test_ledger_rows_aggregate_and_release_exactly():
    ledger = DeviceLedger()
    row_a = ledger.register("m", "weights", 100)
    row_b = ledger.register("m", "weights", 50)
    row_c = ledger.register("m", "kv_pages", 10)
    assert ledger.model_bytes("m") == {"weights": 150, "kv_pages": 10}
    assert ledger.total() == 160
    ledger.release(row_a)
    assert ledger.model_bytes("m") == {"weights": 50, "kv_pages": 10}
    ledger.release(row_a)  # double release: a no-op, never negative
    assert ledger.model_bytes("m")["weights"] == 50
    ledger.release(row_b)
    ledger.release(row_c)
    assert ledger.model_bytes("m") == {}
    assert ledger.total() == 0


def test_ledger_zero_byte_register_is_a_noop():
    ledger = DeviceLedger()
    assert ledger.register("m", "weights", 0) is None
    assert ledger.total() == 0


def test_ledger_release_model_sweeps_all_components():
    ledger = DeviceLedger()
    ledger.register("m", "weights", 5)
    ledger.register("m", "kv_pages", 7)
    ledger.register("other", "weights", 3)
    assert ledger.release_model("m") == 12
    assert ledger.model_bytes("m") == {}
    assert ledger.total() == 3


def test_ledger_component_cardinality_folds_into_overflow():
    ledger = DeviceLedger()
    for index in range(MAX_LEDGER_COMPONENTS + 8):
        ledger.register("m", "component%d" % index, 1)
    components = ledger.model_bytes("m")
    assert len(components) <= MAX_LEDGER_COMPONENTS + 1
    assert components[OVERFLOW_ROW] == 8


def test_model_array_bytes_counts_device_arrays():
    model = _ArrayModel(weights_n=2048)
    assert model_array_bytes(model) == 2048 * 4


# -- ledger pairing across the real lifecycle ------------------------------


def test_load_unload_leaves_no_ledger_residue():
    stats = devstats_mod.get()
    core = build_core([])
    name = "devstats_load_model"
    core.repository.add_factory(name, lambda: _ArrayModel(name))
    before = stats.ledger.model_bytes(name)
    assert before == {}
    try:
        core.load_model(name, warmup=False)
        rows = stats.ledger.model_bytes(name)
        assert rows.get("weights") == 1024 * 4
        # Re-load replaces the weights row instead of stacking on it.
        core.load_model(name, warmup=False)
        assert stats.ledger.model_bytes(name).get("weights") == 1024 * 4
        core.unload_model(name)
        assert stats.ledger.model_bytes(name) == {}
    finally:
        core.shutdown()


def test_replica_reinit_replaces_row_without_residue():
    from client_tpu.server.replicas import ReplicaSet

    stats = devstats_mod.get()
    name = "devstats_replica_model"
    base = _ArrayModel(name)
    base.instance_group_count = 2
    replica_set = ReplicaSet(base, factory=lambda: _ArrayModel(name),
                             count=2)
    try:
        rows = stats.ledger.model_bytes(name)
        # replica 0 shares the base (covered by the weights row);
        # replica 1 holds its own executable.
        assert rows.get("replica:1") == 1024 * 4
        replica_set._reinitialize(replica_set.replicas[1])
        rows = stats.ledger.model_bytes(name)
        assert rows.get("replica:1") == 1024 * 4  # replaced, not added
    finally:
        replica_set.stop()
    assert stats.ledger.model_bytes(name) == {}


def test_kv_pool_row_registered_and_crash_rebuild_releases():
    stats = devstats_mod.get()
    core = build_core([])
    try:
        from client_tpu.models.llm import LlmModel

        model = LlmModel(name="devstats_llm", decode_lanes=2,
                         kv_pages=8)
        core.repository.add_model(model)
        assert stats.ledger.model_bytes("devstats_llm") == {}
        out = list(model.infer_stream({
            "text_input": np.array([b"hello there"], dtype=np.object_),
            "max_tokens": np.array([2], dtype=np.int32),
        }))
        assert out
        rows = stats.ledger.model_bytes("devstats_llm")
        assert rows.get("kv_pages", 0) > 0
        pool_bytes = rows["kv_pages"]
        # Crash: the pool's device arrays are dropped wholesale — the
        # ledger row must go with them, and a rebuild re-registers
        # exactly one row.
        model._crash("injected crash", model._gen)
        assert "kv_pages" not in stats.ledger.model_bytes(
            "devstats_llm")
        out = list(model.infer_stream({
            "text_input": np.array([b"again"], dtype=np.object_),
            "max_tokens": np.array([2], dtype=np.int32),
        }))
        assert out
        assert stats.ledger.model_bytes(
            "devstats_llm")["kv_pages"] == pool_bytes
        core.unload_model("devstats_llm")
        assert stats.ledger.model_bytes("devstats_llm") == {}
    finally:
        core.shutdown()


def test_arena_region_rows_pair_create_destroy():
    pytest.importorskip("jax")
    from client_tpu.server.tpu_arena import TpuArena

    stats = devstats_mod.get()
    before = stats.ledger.model_bytes("arena").get("regions", 0)
    arena = TpuArena()
    handle = arena.create_region(4096, 0)
    region_id = json.loads(handle)["region_id"]
    assert stats.ledger.model_bytes("arena")["regions"] == before + 4096
    arena.destroy_region(region_id)
    assert stats.ledger.model_bytes("arena").get("regions", 0) == before


# -- busy time -------------------------------------------------------------


def test_busy_counter_monotonic_under_concurrent_fused_executions():
    stats = devstats_mod.get()
    core = build_core(["simple_cache"])
    try:
        base = dict(stats.busy_snapshot())

        def worker(offset):
            for index in range(6):
                core.infer(_simple_request(
                    "simple_cache", batch=1,
                    seed=offset * 100 + index))

        pool = [threading.Thread(target=worker, args=(i,))
                for i in range(4)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        mid = dict(stats.busy_snapshot())
        assert sum(mid.values()) > sum(base.values())
        for _ in range(4):
            core.infer(_simple_request("simple_cache", batch=1,
                                       seed=999))
        after = dict(stats.busy_snapshot())
        # Monotonic per device between scrapes.
        for key, value in mid.items():
            assert after.get(key, 0) >= value
        duty = stats.duty_cycle()
        assert duty and all(v >= 0 for v in duty.values())
    finally:
        core.shutdown()


def test_busy_disabled_arm_records_nothing():
    stats = DeviceStats(enabled=False)
    stats.record_busy("CPU-0", 1_000_000)
    assert stats.busy_snapshot() == {}


# -- compile telemetry -----------------------------------------------------


def test_compile_counter_increments_on_forced_shape_bucket_miss():
    if devstats_mod.listener_mode() != "monitoring":
        pytest.skip("jax.monitoring unavailable")
    from client_tpu.models.add_sub import AddSub

    stats = devstats_mod.get()
    name = "devstats_bucket_model"
    # device != "cpu" keeps AddSub off its host-numpy shortcut, so
    # every fused execution goes through the jitted kernel and a
    # fresh shape bucket really compiles.
    model = AddSub(name=name, datatype="INT32", shape=(16,),
                   device="default")
    model.max_batch_size = 4
    model.dynamic_batching = True
    model.preferred_batch_sizes = [1, 2]
    model.max_queue_delay_us = 100
    core = build_core([])
    core.repository.add_model(model)
    try:
        core.infer(_simple_request(name, batch=1))
        first = stats.compile_snapshot().get(name, {"count": 0})
        assert first["count"] >= 1  # bucket b1 compiled
        # Force a shape-bucket miss: a batch-2 request pads to the
        # next preferred size and hits a bucket XLA never traced.
        core.infer(_simple_request(name, batch=2))
        second = stats.compile_snapshot()[name]
        assert second["count"] > first["count"]
        assert any(shape.startswith("b") for shape in second["shapes"])
        # The same bucket again: steady state, no recompile.
        core.infer(_simple_request(name, batch=2))
        assert stats.compile_snapshot()[name]["count"] == \
            second["count"]
    finally:
        core.shutdown()


def test_recompile_storm_stamps_incident_hook():
    stats = DeviceStats(enabled=True)
    stamped = []
    stats.add_incident_hook(lambda model, label: stamped.append(
        (model, label)))
    for _ in range(devstats_mod.STORM_COMPILES):
        stats.record_compile("stormy", "b1", 1_000_000)
    assert stamped
    model, label = stamped[0]
    assert model == "stormy"
    assert label.startswith("recompile_storm")
    # Re-fire is suppressed inside the window (one stamp per storm,
    # not one per compile).
    stats.record_compile("stormy", "b1", 1_000_000)
    assert len(stamped) == 1


def test_compile_shape_cardinality_bounded():
    stats = DeviceStats(enabled=True)
    for index in range(devstats_mod.MAX_COMPILE_SHAPES + 10):
        stats.record_compile("m", "b%d" % index, 1000)
    shapes = stats.compile_snapshot()["m"]["shapes"]
    assert len(shapes) <= devstats_mod.MAX_COMPILE_SHAPES + 1
    assert shapes[devstats_mod.OVERFLOW_SHAPE] == 10


def test_compile_families_render_on_metrics():
    core = build_core(["simple"])
    try:
        core.infer(_simple_request("simple"))
        text = core.metrics_text()
        assert "tpu_device_busy_us_total" in text
        assert "tpu_device_stats_errors_total" in text
        if devstats_mod.listener_mode() == "monitoring":
            assert "tpu_compile_total" in text
            assert "tpu_compile_duration_us_bucket" in text
    finally:
        core.shutdown()


# -- statistics proto ------------------------------------------------------


def test_device_stats_block_in_statistics_proto():
    core = build_core([])
    name = "devstats_proto_model"
    core.repository.add_factory(name, lambda: _ArrayModel(name))
    try:
        core.load_model(name, warmup=False)
        response = core.model_statistics(name)
        stat = response.model_stats[0]
        assert stat.device_stats.hbm_bytes == 1024 * 4
        components = {row.component: row.hbm_bytes
                      for row in stat.device_stats.components}
        assert components.get("weights") == 1024 * 4
    finally:
        core.shutdown()


# -- profiler capture ------------------------------------------------------


def test_profile_capture_bounded_and_chrome_loadable():
    core = build_core(["simple"])
    try:
        stop = threading.Event()

        def traffic():
            while not stop.is_set():
                core.infer(_simple_request("simple"))

        thread = threading.Thread(target=traffic, daemon=True)
        thread.start()
        try:
            # duration is clamped to the [10ms, 10s] bound — a bogus
            # negative duration cannot wedge the single-flight slot.
            doc = core.debug_profile(duration_ms=-50)
        finally:
            stop.set()
            thread.join(timeout=5)
        assert doc["duration_ms"] == devstats_mod.PROFILE_MIN_MS
        assert doc["coalesced"] is False
        assert doc["requests_captured"] >= 0
        with open(doc["chrome_trace"]) as f:
            events = json.load(f)  # strict JSON: loadable as written
        assert isinstance(events, list)
    finally:
        core.shutdown()


def test_profile_capture_taps_requests_even_with_flight_off():
    core = build_core(["simple"])
    try:
        core.flight.enabled = False
        box = {}

        def capture():
            box["doc"] = core.debug_profile(duration_ms=400)

        thread = threading.Thread(target=capture)
        thread.start()
        deadline = time.monotonic() + 10.0
        while not core.devstats.profiler.armed \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert core.devstats.profiler.armed
        # Serve WHILE the window is armed — these are the requests the
        # span tap must capture even with the flight recorder off.
        seed = 0
        while core.devstats.profiler.armed and seed < 10_000:
            seed += 1
            core.infer(_simple_request("simple", seed=seed))
        thread.join(timeout=30)
        doc = box["doc"]
        assert doc["requests_captured"] >= 1
        with open(doc["chrome_trace"]) as f:
            events = json.load(f)
        assert any(e.get("name") == "device_execute" for e in events)
    finally:
        core.flight.enabled = True
        core.shutdown()


def test_profile_concurrent_captures_coalesce_single_flight():
    core = build_core(["simple"])
    try:
        captures_before = core.devstats.profiler.capture_count
        results = []
        lock = threading.Lock()

        def capture():
            doc = core.debug_profile(duration_ms=300)
            with lock:
                results.append(doc)

        threads = [threading.Thread(target=capture) for _ in range(3)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=30)
        assert len(results) == 3
        leaders = [doc for doc in results if not doc["coalesced"]]
        followers = [doc for doc in results if doc["coalesced"]]
        assert len(leaders) >= 1
        assert len(followers) >= 1
        # The coalesced callers share the leader's artifact.
        assert followers[0]["chrome_trace"] == \
            leaders[0]["chrome_trace"]
        assert core.devstats.profiler.capture_count \
            == captures_before + len(leaders)
    finally:
        core.shutdown()


def test_profile_fallback_arm_when_jax_profiler_unsupported(
        monkeypatch):
    core = build_core(["simple"])
    try:
        def boom(*_args, **_kwargs):
            raise RuntimeError("no profiler on this platform")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        doc = core.debug_profile(duration_ms=30)
        assert doc["jax_supported"] is False
        assert doc["mode"] == "spans"
        assert "unsupported on this platform" in doc["jax_error"]
        assert doc["chrome_trace"]  # the span arm still delivers
    finally:
        core.shutdown()


# -- the three transports --------------------------------------------------


def test_profile_endpoint_http_embed():
    core = build_core(["simple"])
    try:
        status, _headers, body = http_call(
            core, "GET", "/v2/debug/profile?duration_ms=20", {}, b"")
        assert status == 200
        doc = json.loads(body)
        assert doc["duration_ms"] == 20
        assert "chrome_trace" in doc
    finally:
        core.shutdown()


def test_profile_endpoint_aiohttp():
    core = build_core(["simple"])
    runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    try:
        url = ("http://127.0.0.1:%d/v2/debug/profile?duration_ms=20"
               % runner.port)
        with urllib.request.urlopen(url, timeout=30) as response:
            doc = json.loads(response.read())
        assert doc["duration_ms"] == 20
        assert "chrome_trace" in doc
    finally:
        runner.stop()
        core.shutdown()


def test_profile_endpoint_grpc():
    import grpc

    core = build_core(["simple"])
    handle = start_grpc_server(core=core, address="127.0.0.1:0")
    try:
        channel = grpc.insecure_channel(handle.address)
        profile = channel.unary_unary(
            "/inference.Debug/Profile",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        doc = json.loads(profile(b'{"duration_ms": 20}', timeout=30))
        assert doc["duration_ms"] == 20
        assert "chrome_trace" in doc
        channel.close()
    finally:
        handle.stop()


# -- /v2/debug devices section ---------------------------------------------


def test_debug_devices_section_present_and_lint_clean():
    core = build_core(["simple"])
    try:
        core.infer(_simple_request("simple"))
        doc = core.debug_snapshot()
        devices = doc["devices"]
        for key in ("ledger", "busy_us", "duty_cycle", "compiles",
                    "profiler", "scrape_errors"):
            assert key in devices
        assert lint_debug_snapshot(devices) == []
        assert lint_debug_snapshot(doc) == []
    finally:
        core.shutdown()


def test_devstats_errors_counter_renders_and_counts():
    stats = DeviceStats(enabled=True)
    stats._note_scrape_error()
    stats._note_scrape_error()
    lines = stats.render_metrics()
    assert "tpu_device_stats_errors_total 2" in lines
