"""Examples-as-smoke-tests (parity: SURVEY.md §4 tier 4 — the
reference's simple_* clients double as protocol conformance checks).
Every example runs against one live in-process server and must print
PASS."""

import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # runs every example against live servers

REPO = pathlib.Path(__file__).resolve().parent.parent
EXAMPLES = REPO / "examples"

GRPC_EXAMPLES = [
    "grpc_explicit_int_content_client.py",
    "grpc_explicit_byte_content_client.py",
    "grpc_explicit_int8_content_client.py",
    "simple_grpc_shm_string_client.py",
    "simple_grpc_aio_sequence_stream_infer_client.py",
    "simple_grpc_keepalive_client.py",
    "simple_grpc_infer_client.py",
    "simple_grpc_string_infer_client.py",
    "simple_grpc_async_infer_client.py",
    "simple_grpc_sequence_sync_client.py",
    "simple_grpc_sequence_stream_infer_client.py",
    "simple_grpc_shm_client.py",
    "simple_grpc_tpushm_client.py",
    "simple_grpc_health_metadata_client.py",
    "simple_grpc_model_control_client.py",
    "simple_grpc_aio_infer_client.py",
    "decoupled_grpc_stream_infer_client.py",
    "grpc_client.py",
    "grpc_image_client.py",
    "simple_grpc_custom_repeat_client.py",
]

HTTP_EXAMPLES = [
    "simple_http_health_metadata_client.py",
    "simple_http_model_control_client.py",
    "simple_http_sequence_sync_client.py",
    "simple_http_infer_client.py",
    "simple_http_async_infer_client.py",
    "simple_http_aio_infer_client.py",
    "simple_http_shm_client.py",
    "simple_http_string_infer_client.py",
    "simple_http_shm_string_client.py",
]


@pytest.fixture(scope="module")
def example_server():
    from client_tpu.server.app import build_core, start_grpc_server
    from client_tpu.server.http_server import start_http_server_thread

    core = build_core(
        ["simple", "simple_string", "simple_sequence", "repeat_int32",
         "add_sub_fp32", "add_sub_int8", "resnet50", "ensemble_image"]
    )
    grpc_handle = start_grpc_server(core=core)
    http_runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield {
        "grpc": grpc_handle.address,
        "http": "127.0.0.1:%d" % http_runner.port,
    }
    http_runner.stop()
    grpc_handle.stop()


def _run_example_args(name, args, timeout=300):
    import os

    env = dict(os.environ)
    # An ambient deployment route would redirect the self-hosted
    # cross-host example's pulls to the wrong endpoint.
    env.pop("CLIENT_TPU_ARENA_URL", None)
    # The cross-host example builds server cores (imports jax) in this
    # subprocess: both knobs must be set before the interpreter starts
    # or the image's sitecustomize brings up the axon TPU platform
    # (minutes of init, possible relay wedge). Harmless for the
    # pure-client examples.
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    proc = subprocess.run(
        [sys.executable, str(EXAMPLES / name)] + args,
        capture_output=True, text=True, timeout=timeout, env=env,
    )
    assert proc.returncode == 0, "%s failed:\n%s\n%s" % (
        name, proc.stdout[-2000:], proc.stderr[-2000:]
    )
    assert "PASS" in proc.stdout, proc.stdout


def _run_example(name: str, url: str):
    _run_example_args(name, ["-u", url], timeout=120)


@pytest.mark.parametrize("name", GRPC_EXAMPLES)
def test_grpc_example(example_server, name):
    _run_example(name, example_server["grpc"])


@pytest.mark.parametrize("name", HTTP_EXAMPLES)
def test_http_example(example_server, name):
    _run_example(name, example_server["http"])


def test_cross_host_example():
    # Self-hosts its two "hosts" (owner + serving server), so it takes
    # no -u; the serving host redeems the owner's handle via DCN pull.
    _run_example_args("tpu_shm_cross_host_client.py", [])


def test_multi_rank_example(example_server):
    # Two native analyzer ranks over the builtin TCP coordinator
    # (launcher-free mpirun); skips itself cleanly if the native
    # harness is not built.
    binary = REPO / "native" / "build" / "perf_analyzer"
    if not binary.exists():
        pytest.skip("native harness not built")
    _run_example_args("multi_rank_perf_analyzer.py",
                      ["-u", example_server["grpc"], "-n", "2"])


CPP_GRPC_EXAMPLES = [
    "simple_grpc_infer_client",
    "simple_grpc_async_infer_client",
    "simple_grpc_string_infer_client",
    "simple_grpc_stream_infer_client",
    "simple_grpc_shm_client",
    "simple_grpc_tpushm_client",
    "simple_grpc_sequence_sync_client",
    "simple_grpc_health_metadata_client",
    "simple_grpc_model_control_client",
    "simple_grpc_keepalive_client",
    "simple_grpc_custom_repeat_client",
    "simple_grpc_sequence_stream_client",
    "simple_grpc_custom_args_client",
    "ensemble_image_client",
    "image_client",
]

CPP_HTTP_EXAMPLES = [
    "simple_http_infer_client",
    "simple_http_string_infer_client",
    "simple_http_async_infer_client",
    "simple_http_health_metadata_client",
    "simple_http_model_control_client",
    "simple_http_shm_client",
    "simple_http_sequence_sync_client",
]


def _run_native_example(name: str, url: str):
    binary = REPO / "native" / "build" / name
    if not binary.exists():
        pytest.skip("native examples not built (run test_native first)")
    proc = subprocess.run(
        [str(binary), "-u", url], capture_output=True, text=True, timeout=60
    )
    assert proc.returncode == 0, "%s failed:\n%s\n%s" % (
        name, proc.stdout[-2000:], proc.stderr[-2000:]
    )
    assert "PASS" in proc.stdout


@pytest.mark.parametrize("name", CPP_GRPC_EXAMPLES)
def test_cpp_grpc_example(example_server, name):
    _run_native_example(name, example_server["grpc"])


@pytest.mark.parametrize("name", CPP_HTTP_EXAMPLES)
def test_cpp_http_example(example_server, name):
    _run_native_example(name, example_server["http"])


# -- image / ensemble / reuse clients (richer argument surfaces) ----------


def test_http_tpushm_client(example_server):
    """HTTP protocol + TPU-arena zero-copy I/O (the reference's
    simple_http_cudashm_client analogue): registration verbs ride
    REST while the arena service rides the gRPC port."""
    _run_example_args(
        "simple_http_tpushm_client.py",
        ["-u", example_server["http"],
         "--arena-url", example_server["grpc"],
         "-m", "add_sub_fp32"],
        timeout=120,
    )


@pytest.mark.parametrize("extra", [
    [],                                # sync, argmax output
    ["-c", "3", "-s", "INCEPTION"],    # server-side classification
    ["-a"],                            # async
    ["--shared-memory", "system"],
    ["--shared-memory", "tpu"],        # the BASELINE config #2 shape
    ["--streaming", "-b", "1"],
])
def test_image_client(example_server, extra):
    _run_example_args(
        "image_client.py",
        ["-m", "resnet50", "-b", "2", "-u", example_server["grpc"]] + extra)


def test_image_client_http(example_server):
    _run_example_args(
        "image_client.py",
        ["-m", "resnet50", "-b", "2", "-i", "http",
         "-u", example_server["http"]])


def test_image_client_real_file(example_server, tmp_path):
    import numpy as np

    Image = pytest.importorskip("PIL.Image")

    path = tmp_path / "img.png"
    Image.fromarray(
        (np.random.default_rng(0).random((64, 48, 3)) * 255).astype("uint8")
    ).save(path)
    _run_example_args(
        "image_client.py",
        ["-m", "resnet50", "-b", "2", "-s", "VGG",
         "-u", example_server["grpc"], str(path)])


def test_image_client_more_images_than_batch(example_server, tmp_path):
    """Surplus images become extra batched requests — every file gets
    classified, none silently dropped."""
    import numpy as np

    Image = pytest.importorskip("PIL.Image")
    rng = np.random.default_rng(0)
    for i in range(5):
        Image.fromarray(
            (rng.random((32, 32, 3)) * 255).astype("uint8")
        ).save(tmp_path / ("img%d.png" % i))
    proc = subprocess.run(
        [sys.executable, str(EXAMPLES / "image_client.py"),
         "-m", "resnet50", "-b", "2", "-u", example_server["grpc"],
         str(tmp_path)],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for i in range(5):
        assert ("img%d.png" % i) in proc.stdout, proc.stdout


@pytest.mark.parametrize("extra", [[], ["--streaming"]])
def test_ensemble_image_client(example_server, extra):
    _run_example_args(
        "ensemble_image_client.py",
        ["-u", example_server["grpc"], "-b", "2"] + extra)


def test_reuse_infer_objects(example_server):
    _run_example_args(
        "reuse_infer_objects_client.py",
        ["-u", example_server["grpc"], "--http-url",
         example_server["http"]])


def test_custom_args_client(example_server):
    _run_example_args(
        "simple_grpc_custom_args_client.py", ["-u", example_server["grpc"]])


def test_memory_growth(example_server):
    _run_example_args(
        "memory_growth_test.py",
        ["-u", example_server["grpc"], "-n", "600"])


def test_cpp_reuse_infer_objects(example_server):
    """Needs both protocol endpoints (-u grpc, -w http)."""
    binary = REPO / "native" / "build" / "reuse_infer_objects_client"
    if not binary.exists():
        pytest.skip("native examples not built (run test_native first)")
    proc = subprocess.run(
        [str(binary), "-u", example_server["grpc"],
         "-w", example_server["http"]],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS" in proc.stdout
