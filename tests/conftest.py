"""Test config: force JAX onto a virtual 8-device CPU platform so
sharding/mesh tests run anywhere (the driver separately dry-runs the
multi-chip path). Must run before jax is imported anywhere."""

import os
import sys

# Force CPU even if the outer environment selects a TPU platform.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize registers a TPU platform plugin and forces
# it programmatically, so the env var alone is not enough — override
# the jax config before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
