"""Integration tests for tpu_serverd, the native C++ gRPC front-end
(native/server/): the grpcio-based Python client drives the native
server the same way cc_client tests drive the grpcio server — both
directions of the wire protocol are covered by real cross-stack pairs.
"""

import pathlib
import subprocess
import threading

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # tpu_serverd e2e (needs native build)

REPO = pathlib.Path(__file__).resolve().parent.parent
SERVERD = REPO / "native" / "build" / "tpu_serverd"


@pytest.fixture(scope="module")
def serverd_ports():
    if not SERVERD.exists():
        pytest.skip("tpu_serverd not built (run tests/test_native.py first)")
    import os

    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    # An ambient deployment route would override the bound address the
    # owner_url assertions expect.
    env.pop("CLIENT_TPU_ARENA_URL", None)
    proc = subprocess.Popen(
        [str(SERVERD), "--port", "0", "--http-port", "0",
         "--models", "simple"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=str(REPO), env=env,
    )
    try:
        line = proc.stdout.readline().strip()  # "LISTENING <port>"
        assert line.startswith("LISTENING "), line
        http_line = proc.stdout.readline().strip()  # "LISTENING-HTTP <p>"
        assert http_line.startswith("LISTENING-HTTP "), http_line
        yield {"grpc": "127.0.0.1:%s" % line.split()[1],
               "http": "127.0.0.1:%s" % http_line.split()[1]}
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


@pytest.fixture(scope="module")
def serverd(serverd_ports):
    return serverd_ports["grpc"]


@pytest.fixture()
def client(serverd):
    import client_tpu.grpc as grpcclient

    with grpcclient.InferenceServerClient(serverd) as c:
        yield c


def _simple_inputs():
    import client_tpu.grpc as grpcclient

    in0 = np.arange(16, dtype=np.int32)
    in1 = np.ones(16, dtype=np.int32)
    inputs = [
        grpcclient.InferInput("INPUT0", [16], "INT32"),
        grpcclient.InferInput("INPUT1", [16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(in0)
    inputs[1].set_data_from_numpy(in1)
    return in0, in1, inputs


def test_health_and_metadata(client):
    assert client.is_server_live()
    assert client.is_server_ready()
    assert client.is_model_ready("simple")
    meta = client.get_server_metadata()
    assert meta.name == "client_tpu_server"
    model = client.get_model_metadata("simple")
    assert [t.name for t in model.inputs] == ["INPUT0", "INPUT1"]


def test_unary_infer(client):
    in0, in1, inputs = _simple_inputs()
    result = client.infer("simple", inputs)
    np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
    np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), in0 - in1)


def test_error_status_mapping(client):
    from client_tpu.utils import InferenceServerException

    with pytest.raises(InferenceServerException) as exc:
        client.get_model_metadata("no_such_model")
    assert exc.value.status() == "NOT_FOUND"


def test_streaming(client):
    import queue

    in0, in1, inputs = _simple_inputs()
    q = queue.Queue()
    client.start_stream(callback=lambda r, e: q.put((r, e)))
    n = 4
    for _ in range(n):
        client.async_stream_infer("simple", inputs)
    for _ in range(n):
        result, error = q.get(timeout=15)
        assert error is None
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
    client.stop_stream()


def test_concurrent_unary(serverd):
    """Many streams multiplexed over independent client connections:
    exercises the worker pool + per-stream ordering under load."""
    import client_tpu.grpc as grpcclient

    in0, in1, _ = _simple_inputs()
    errors = []

    def worker():
        try:
            with grpcclient.InferenceServerClient(serverd) as c:
                for _ in range(10):
                    _, _, inputs = _simple_inputs()
                    result = c.infer("simple", inputs)
                    np.testing.assert_array_equal(
                        result.as_numpy("OUTPUT0"), in0 + in1)
        except Exception as e:  # noqa: BLE001 — collected for assert
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors


def test_system_shared_memory_verbs(client):
    import client_tpu.utils.shared_memory as shm

    handle = shm.create_shared_memory_region("ns_in0", "/ns_serverd", 64)
    try:
        shm.set_shared_memory_region(handle,
                                     [np.arange(16, dtype=np.int32)])
        client.register_system_shared_memory("ns_in0", "/ns_serverd", 64)
        status = client.get_system_shared_memory_status()
        assert "ns_in0" in status.regions
        client.unregister_system_shared_memory("ns_in0")
    finally:
        shm.destroy_shared_memory_region(handle)


def test_statistics_accumulate(serverd):
    import client_tpu.grpc as grpcclient

    with grpcclient.InferenceServerClient(serverd) as c:
        before = c.get_inference_statistics("simple") \
            .model_stats[0].inference_stats.success.count
        _, _, inputs = _simple_inputs()
        c.infer("simple", inputs)
        after = c.get_inference_statistics("simple") \
            .model_stats[0].inference_stats.success.count
    assert after == before + 1


def test_arena_pull_through_native_front_end(serverd):
    """The DCN pull rides the native C++ h2 transport end to end: a
    consumer arena pulls a region the native server's arena owns, via
    the server-streaming PullRegion RPC over a real channel."""
    import client_tpu.utils.tpu_shared_memory as tpushm
    from client_tpu.server.arena_pull import pull_region
    from client_tpu.server.tpu_arena import TpuArena

    tpushm.set_arena_endpoint(serverd)
    try:
        payload = np.random.default_rng(3).random((8, 32)).astype(
            np.float32)
        handle = tpushm.create_shared_memory_region(
            "pull_src", payload.nbytes, 0)
        try:
            tpushm.set_shared_memory_region(handle, [payload])
            raw = tpushm.get_raw_handle(handle)
            # Handles minted by the native front-end carry the route
            # (SetArenaPublicUrl runs post-bind, pre-serve).
            import json

            assert json.loads(raw).get("owner_url") == serverd
            consumer = TpuArena()
            local = pull_region(serverd, raw, consumer, chunk_bytes=256)
            region_id = json.loads(local)["region_id"]
            got = np.asarray(consumer.as_typed_array(
                region_id, 0, payload.nbytes, "FP32", [8, 32]))
            np.testing.assert_array_equal(got, payload)
        finally:
            tpushm.destroy_shared_memory_region(handle)
    finally:
        tpushm.reset_arena_endpoint()


def test_http_front_end_infer(serverd_ports):
    """The Python HTTP client (binary protocol, own pooled transport)
    drives tpu_serverd's native HTTP/1.1 front-end."""
    import client_tpu.http as httpclient

    with httpclient.InferenceServerClient(serverd_ports["http"]) as c:
        assert c.is_server_live()
        meta = c.get_model_metadata("simple")
        assert meta["name"] == "simple"
        in0 = np.arange(16, dtype=np.int32)
        in1 = np.ones(16, dtype=np.int32)
        inputs = [httpclient.InferInput("INPUT0", [16], "INT32"),
                  httpclient.InferInput("INPUT1", [16], "INT32")]
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1)
        result = c.infer("simple", inputs)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)


def test_http_front_end_errors_and_keepalive(serverd_ports):
    import client_tpu.http as httpclient
    from client_tpu.utils import InferenceServerException

    with httpclient.InferenceServerClient(serverd_ports["http"]) as c:
        with pytest.raises(InferenceServerException):
            c.get_model_metadata("no_such_model")
        # Several requests over one keep-alive connection.
        for _ in range(5):
            assert c.is_server_ready()
