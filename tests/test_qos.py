"""Multi-tenant QoS tests: priority coercion/validation, per-priority
queues and strict-then-weighted dispatch in the dynamic batcher,
graceful load shedding (displacement at a full queue + watermark),
tenant token-bucket quotas with Retry-After, QoS observability
(ModelStatistics rows, Prometheus families, span attributes), the
priority-param round trip over HTTP + gRPC sync + aio, and the
overload chaos scenario."""

import asyncio
import json
import threading
import time

import numpy as np
import pytest

from client_tpu.server.batcher import DynamicBatcher, _params_fingerprint
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.server.qos import (
    ANONYMOUS_TENANT,
    TenantPolicy,
    TenantQuotaManager,
    coerce_priority,
)
from client_tpu.utils import InferenceServerException


# -- priority coercion (the silent-drop fix) ------------------------------


def test_coerce_priority_accepts_wire_forms():
    assert coerce_priority(1, 3) == 1
    assert coerce_priority("2", 3) == 2
    assert coerce_priority(3.0, 3) == 3
    assert coerce_priority("2.0", 3) == 2


def test_coerce_priority_default_level():
    # absent/0 -> default_priority_level, or the middle level when
    # that is 0 too
    assert coerce_priority(None, 4, default_level=2) == 2
    assert coerce_priority(0, 4, default_level=1) == 1
    assert coerce_priority(None, 4) == 2  # (4 + 1) // 2
    assert coerce_priority(None, 5) == 3
    # disabled levels: always class 0
    assert coerce_priority(7, 0) == 0


@pytest.mark.parametrize("bad", [-1, 5, "9", "nope", object()])
def test_coerce_priority_rejects_invalid(bad):
    with pytest.raises(InferenceServerException) as excinfo:
        coerce_priority(bad, 4)
    assert excinfo.value.status() == "INVALID_ARGUMENT"
    assert "0..4" in str(excinfo.value)  # documented accepted range


def test_qos_params_excluded_from_fusion_fingerprint():
    base = _params_fingerprint({"custom": 1})
    assert _params_fingerprint(
        {"custom": 1, "priority": 1, "tenant": "a", "timeout": 5}) == base
    # non-QoS params still fragment
    assert _params_fingerprint({"custom": 2}) != base


# -- tenant quotas --------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def test_tenant_quota_spec_parsing():
    manager = TenantQuotaManager.from_spec(
        "default=rate:100,burst:20,concurrency:8;bulk=rate:10")
    assert manager.enabled
    assert manager._default.rate_per_s == 100
    assert manager._default.burst == 20
    assert manager._default.concurrency == 8
    assert manager._policies["bulk"].rate_per_s == 10
    assert manager._policies["bulk"].burst == 10  # defaults to rate
    with pytest.raises(ValueError):
        TenantQuotaManager.from_spec("oops")
    with pytest.raises(ValueError):
        TenantQuotaManager.from_spec("a=frobnicate:1")


def test_token_bucket_rate_and_refill():
    clock = FakeClock()
    manager = TenantQuotaManager(
        default=TenantPolicy(rate_per_s=10, burst=2), clock=clock)
    manager.acquire("t")
    manager.acquire("t")  # burst exhausted
    with pytest.raises(InferenceServerException) as excinfo:
        manager.acquire("t")
    error = excinfo.value
    assert error.status() == "RESOURCE_EXHAUSTED"
    # refill time for one token at 10/s = 100ms
    assert error.retry_after_s == pytest.approx(0.1, abs=0.02)
    clock.now += 0.11  # wait out the advised backoff
    manager.acquire("t")  # token refilled
    snap = manager.snapshot()["t"]
    assert snap["admitted"] == 3
    assert snap["rejected"] == 1
    assert snap["inflight"] == 3


def test_concurrency_cap_and_release():
    manager = TenantQuotaManager(
        default=TenantPolicy(concurrency=2))
    manager.acquire("t")
    manager.acquire("t")
    with pytest.raises(InferenceServerException) as excinfo:
        manager.acquire("t")
    assert excinfo.value.status() == "RESOURCE_EXHAUSTED"
    assert excinfo.value.retry_after_s > 0
    manager.release("t", ok=True, duration_ns=5_000_000)
    manager.acquire("t")  # slot freed
    snap = manager.snapshot()["t"]
    assert snap["completed"] == 1
    assert snap["total_ns"] == 5_000_000


def test_quota_rejects_are_retryable_with_server_pacing():
    from client_tpu.robust import RetryPolicy, retry_after_of

    policy = RetryPolicy()
    error = InferenceServerException("over quota",
                                     status="RESOURCE_EXHAUSTED")
    error.retry_after_s = 0.25
    assert policy.is_retryable(error)
    assert policy.is_retryable(InferenceServerException("x", status="429"))
    assert retry_after_of(error) == 0.25


# -- batcher priority scheduling ------------------------------------------


class GatedModel(ServedModel):
    """Execution blocks on a gate; records executed values in order so
    dispatch order is observable."""

    max_batch_size = 8
    dynamic_batching = True

    def __init__(self, name="qos_gated"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("IN", "FP32", [4])]
        self.outputs = [TensorSpec("OUT", "FP32", [4])]
        self.executions = []
        self.gate = threading.Event()

    def infer(self, inputs, parameters=None):
        self.gate.wait()
        array = np.asarray(inputs["IN"])
        self.executions.append([float(v) for v in array[:, 0]])
        return {"OUT": array * 2.0}


def _submit(batcher, i, params=None, results=None):
    def run():
        try:
            out, _, _ = batcher.infer(
                {"IN": np.full((1, 4), float(i), np.float32)},
                dict(params or {}), 1)
            results[i] = ("ok", float(out["OUT"][0, 0]))
        except InferenceServerException as e:
            results[i] = (e.status(), str(e))

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    return thread


def _wait_for(predicate, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while not predicate() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert predicate()


def test_priority_one_overtakes_bulk_backlog():
    """A priority-1 request enqueued BEHIND a bulk backlog dispatches
    in the very next execution (dispatch singles: preferred size 1)."""
    model = GatedModel()
    batcher = DynamicBatcher(model, max_queue_delay_us=1000,
                             preferred_batch_sizes=[1], pipeline_depth=1,
                             priority_levels=2, default_priority_level=2)
    results = {}
    threads = [_submit(batcher, 0, results=results)]
    time.sleep(0.15)  # request 0 dispatched, holds the gate
    threads += [_submit(batcher, i, params={"priority": 2},
                        results=results) for i in (1, 2, 3)]
    time.sleep(0.1)  # bulk backlog queued
    threads += [_submit(batcher, 9, params={"priority": 1},
                        results=results)]
    time.sleep(0.1)
    model.gate.set()
    for thread in threads:
        thread.join(timeout=10)
    batcher.stop()
    assert all(r[0] == "ok" for r in results.values())
    order = [v for execution in model.executions for v in execution]
    # 0 was in flight; 9 (priority 1) must beat every queued bulk
    assert order.index(9.0) < min(order.index(v) for v in (1.0, 2.0, 3.0))


def test_mixed_priority_requests_fuse_into_one_execution():
    model = GatedModel()
    batcher = DynamicBatcher(model, max_queue_delay_us=300_000,
                             preferred_batch_sizes=[8],
                             priority_levels=2, default_priority_level=2)
    results = {}
    threads = [
        _submit(batcher, i, params={"priority": 1 + i % 2},
                results=results)
        for i in range(4)
    ]
    _wait_for(lambda: batcher.stats_snapshot()["pending_count"] == 4)
    model.gate.set()
    for thread in threads:
        thread.join(timeout=10)
    batcher.stop()
    assert all(results[i][0] == "ok" for i in range(4))
    assert len(model.executions) == 1  # one fused execution
    # within the fused batch, priority-1 members seated first
    first = model.executions[0]
    assert set(first) == {0.0, 1.0, 2.0, 3.0}
    p1 = {i for i in range(4) if 1 + i % 2 == 1}
    assert {first.index(float(i)) for i in p1} == {0, 1}


def test_full_queue_displaces_newest_bulk_for_priority_one():
    model = GatedModel()
    sheds = []
    batcher = DynamicBatcher(model, max_queue_delay_us=200_000,
                             preferred_batch_sizes=[1], pipeline_depth=1,
                             max_queue_size=2,
                             priority_levels=2, default_priority_level=2,
                             shed_hook=lambda p: sheds.append(p))
    results = {}
    threads = [_submit(batcher, 0, params={"priority": 2},
                       results=results)]
    time.sleep(0.15)  # 0 in flight
    threads += [_submit(batcher, i, params={"priority": 2},
                        results=results) for i in (1, 2)]
    _wait_for(lambda: batcher.stats_snapshot()["pending_count"] == 2)
    # queue full of bulk: the priority-1 arrival displaces the NEWEST
    # bulk waiter (2) instead of being rejected
    threads += [_submit(batcher, 9, params={"priority": 1},
                        results=results)]
    _wait_for(lambda: 2 in results)
    assert results[2][0] == "UNAVAILABLE"
    assert "shed" in results[2][1]
    model.gate.set()
    for thread in threads:
        thread.join(timeout=10)
    batcher.stop()
    assert results[9][0] == "ok"
    assert results[1][0] == "ok"
    assert sheds == [2]  # the displaced request's class


def test_full_queue_rejects_same_class_without_displacement():
    model = GatedModel()
    rejects = []
    batcher = DynamicBatcher(model, max_queue_delay_us=200_000,
                             preferred_batch_sizes=[1], pipeline_depth=1,
                             max_queue_size=2,
                             priority_levels=2, default_priority_level=2,
                             reject_hook=lambda p: rejects.append(p))
    results = {}
    threads = [_submit(batcher, 0, results=results)]
    time.sleep(0.15)
    threads += [_submit(batcher, i, results=results) for i in (1, 2)]
    _wait_for(lambda: batcher.stats_snapshot()["pending_count"] == 2)
    threads += [_submit(batcher, 3, results=results)]  # same class
    _wait_for(lambda: 3 in results)
    assert results[3][0] == "UNAVAILABLE"
    model.gate.set()
    for thread in threads:
        thread.join(timeout=10)
    batcher.stop()
    assert rejects == [2]  # default class


def test_watermark_sheds_lowest_class_arrivals():
    model = GatedModel()
    sheds = []
    batcher = DynamicBatcher(model, max_queue_delay_us=200_000,
                             preferred_batch_sizes=[1], pipeline_depth=1,
                             max_queue_size=4, shed_watermark=0.5,
                             priority_levels=2, default_priority_level=2,
                             shed_hook=lambda p: sheds.append(p))
    results = {}
    threads = [_submit(batcher, 0, params={"priority": 2},
                       results=results)]
    time.sleep(0.15)
    threads += [_submit(batcher, i, params={"priority": 2},
                        results=results) for i in (1, 2)]
    _wait_for(lambda: batcher.stats_snapshot()["pending_count"] == 2)
    # depth 2 >= 0.5 * 4: lowest-class arrivals shed with Retry-After,
    # priority-1 arrivals still admitted
    threads += [_submit(batcher, 3, params={"priority": 2},
                        results=results)]
    _wait_for(lambda: 3 in results)
    assert results[3][0] == "UNAVAILABLE"
    assert "watermark" in results[3][1]
    threads += [_submit(batcher, 9, params={"priority": 1},
                        results=results)]
    time.sleep(0.1)
    model.gate.set()
    for thread in threads:
        thread.join(timeout=10)
    batcher.stop()
    assert results[9][0] == "ok"
    assert sheds == [2]


def test_per_priority_queue_policy_caps_and_timeouts():
    model = GatedModel()
    batcher = DynamicBatcher(
        model, max_queue_delay_us=500_000, preferred_batch_sizes=[1],
        pipeline_depth=1, priority_levels=2, default_priority_level=2,
        priority_policies={2: {"max_queue_size": 1,
                               "default_timeout_us": 80_000}})
    results = {}
    threads = [_submit(batcher, 0, params={"priority": 1},
                       results=results)]
    time.sleep(0.15)  # 0 in flight
    threads += [_submit(batcher, 1, params={"priority": 2},
                        results=results)]
    _wait_for(lambda: batcher.stats_snapshot()["pending_count"] == 1)
    # class-2 queue is capped at 1: a second bulk waiter is rejected
    # even though the global queue is unbounded
    threads += [_submit(batcher, 2, params={"priority": 2},
                        results=results)]
    _wait_for(lambda: 2 in results)
    assert results[2][0] == "UNAVAILABLE"
    assert "per-priority" in results[2][1]
    # and the queued class-2 request expires on ITS class default
    _wait_for(lambda: 1 in results)
    assert results[1][0] == "DEADLINE_EXCEEDED"
    model.gate.set()
    threads[0].join(timeout=10)
    batcher.stop()


def test_aged_oldest_slot_prevents_bulk_starvation():
    """Every AGE_EVERY dispatches the globally-oldest request is
    seated first, so sustained priority-1 load cannot starve bulk
    forever (the weighted arm of strict-then-weighted dispatch)."""
    from client_tpu.server.batcher import _Bucket, _Pending

    bucket = _Bucket()
    bulk = _Pending({}, {}, 1, "k", priority=2)
    bucket.append(bulk)
    time.sleep(0.002)
    for _ in range(3):
        bucket.append(_Pending({}, {}, 1, "k", priority=1))
    taken = bucket.take(max_batch=1, full_at=1, age_oldest=True)
    assert taken == [bulk]  # oldest wins the aged slot despite class
    taken = bucket.take(max_batch=1, full_at=1, age_oldest=False)
    assert taken[0].priority == 1


# -- config render + parser round trip ------------------------------------


class QosConfigModel(GatedModel):
    priority_levels = 3
    default_priority_level = 2
    shed_watermark = 0.75
    priority_queue_policies = {
        1: {"max_queue_size": 8},
        3: {"default_timeout_us": 50_000},
    }


def test_config_pb_renders_priority_schema():
    config = QosConfigModel().config_pb()
    batching = config.dynamic_batching
    assert batching.priority_levels == 3
    assert batching.default_priority_level == 2
    assert batching.shed_watermark == pytest.approx(0.75)
    rows = {r.priority_level: r for r in batching.priority_queue_policy}
    assert rows[1].max_queue_size == 8
    assert rows[3].default_timeout_us == 50_000


def test_model_parser_reads_priority_schema():
    from client_tpu.perf.model_parser import ModelParser

    class Backend:
        def model_metadata(self, name, version=""):
            return {"name": "qos_gated", "versions": ["1"],
                    "platform": "jax",
                    "inputs": [{"name": "IN", "datatype": "FP32",
                                "shape": [-1, 4]}],
                    "outputs": [{"name": "OUT", "datatype": "FP32",
                                 "shape": [-1, 4]}]}

        def model_config(self, name, version=""):
            from google.protobuf import json_format

            return json_format.MessageToDict(
                QosConfigModel().config_pb(),
                preserving_proto_field_name=True)

    model = ModelParser().parse(Backend(), "qos_gated")
    assert model.priority_levels == 3
    assert model.default_priority_level == 2
    assert model.shed_watermark == pytest.approx(0.75)


# -- end to end over real transports --------------------------------------


@pytest.fixture(scope="module")
def qos_servers():
    from client_tpu.server.app import build_core, start_grpc_server
    from client_tpu.server.http_server import start_http_server_thread
    from client_tpu.server.qos import TenantQuotaManager

    core = build_core(["simple_qos"], warmup=False)
    core.tenant_quotas = TenantQuotaManager.from_spec(
        "default=rate:10000;limited=rate:2,burst:1;"
        "streamlim=rate:0.2,burst:1")
    grpc_handle = start_grpc_server(core=core)
    http_runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield core, grpc_handle, http_runner
    http_runner.stop()
    grpc_handle.stop()


def _qos_inputs(client_mod):
    inputs = [client_mod.InferInput("INPUT0", [1, 16], "INT32"),
              client_mod.InferInput("INPUT1", [1, 16], "INT32")]
    inputs[0].set_data_from_numpy(np.arange(16, dtype=np.int32)[None])
    inputs[1].set_data_from_numpy(np.ones((1, 16), np.int32))
    return inputs


def _priority_counts(core, model="simple_qos"):
    stats = core.model_statistics(model)
    return {int(r.priority_level): int(r.success_count)
            for r in stats.model_stats[0].priority_stats}


def test_priority_round_trip_http_and_grpc_sync(qos_servers):
    import client_tpu.grpc as grpcclient
    import client_tpu.http as httpclient

    core, grpc_handle, http_runner = qos_servers
    before = _priority_counts(core)
    with httpclient.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port) as client:
        client.infer("simple_qos", _qos_inputs(httpclient), priority=1)
    with grpcclient.InferenceServerClient(grpc_handle.address) as client:
        client.infer("simple_qos", _qos_inputs(grpcclient), priority=1)
        # invalid priority is INVALID_ARGUMENT end to end, not ignored
        with pytest.raises(InferenceServerException) as excinfo:
            client.infer("simple_qos", _qos_inputs(grpcclient),
                         priority=9)
        assert excinfo.value.status() == "INVALID_ARGUMENT"
    after = _priority_counts(core)
    assert after.get(1, 0) - before.get(1, 0) == 2


def test_priority_round_trip_aio(qos_servers):
    import client_tpu.grpc.aio as grpcclient_aio
    import client_tpu.http.aio as httpclient_aio

    core, grpc_handle, http_runner = qos_servers
    before = _priority_counts(core)

    async def run():
        async with grpcclient_aio.InferenceServerClient(
                grpc_handle.address) as client:
            await client.infer("simple_qos", _qos_inputs(grpcclient_aio),
                               priority=1)
        async with httpclient_aio.InferenceServerClient(
                "127.0.0.1:%d" % http_runner.port) as client:
            await client.infer("simple_qos", _qos_inputs(httpclient_aio),
                               priority=1)

    asyncio.run(run())
    after = _priority_counts(core)
    assert after.get(1, 0) - before.get(1, 0) == 2


def test_priority_one_overtakes_full_bulk_backlog_e2e(qos_servers):
    """The satellite's e2e shape over BOTH transports: a gated model
    builds a bulk backlog, a priority-1 request sent last executes
    first once the gate opens."""
    import client_tpu.grpc as grpcclient
    import client_tpu.http as httpclient

    core, grpc_handle, http_runner = qos_servers

    for transport in ("http", "grpc"):
        model = GatedModel(name="qos_gated_%s" % transport)
        model.preferred_batch_sizes = [1]
        model.pipeline_depth = 1
        model.priority_levels = 2
        model.default_priority_level = 2
        core.repository.add_model(model)

        if transport == "http":
            client = httpclient.InferenceServerClient(
                "127.0.0.1:%d" % http_runner.port, concurrency=8)
            mod = httpclient
        else:
            client = grpcclient.InferenceServerClient(grpc_handle.address)
            mod = grpcclient

        def send(value, priority):
            inputs = [mod.InferInput("IN", [1, 4], "FP32")]
            inputs[0].set_data_from_numpy(
                np.full((1, 4), float(value), np.float32))
            client.infer(model.name, inputs, priority=priority)

        threads = [threading.Thread(target=send, args=(0, 2),
                                    daemon=True)]
        threads[0].start()
        time.sleep(0.3)  # 0 dispatched, holds the gate
        for value in (1, 2, 3):
            thread = threading.Thread(target=send, args=(value, 2),
                                      daemon=True)
            thread.start()
            threads.append(thread)
        deadline = time.monotonic() + 5
        while core.model_statistics(model.name).model_stats[0] \
                .pipeline_stats.pending_count < 3 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        hi = threading.Thread(target=send, args=(9, 1), daemon=True)
        hi.start()
        threads.append(hi)
        time.sleep(0.2)
        model.gate.set()
        for thread in threads:
            thread.join(timeout=15)
        client.close()
        order = [v for execution in model.executions for v in execution]
        assert order.index(9.0) < min(
            order.index(v) for v in (1.0, 2.0, 3.0)), \
            "%s: priority-1 did not overtake (%s)" % (transport, order)


def test_mixed_priority_fuses_with_shared_batch_execute_span(
        qos_servers, tmp_path):
    """Mixed-priority concurrent requests still fuse: their traces
    share ONE batch_execute span id, and their queue spans carry the
    priority attribute."""
    core, grpc_handle, _ = qos_servers
    import client_tpu.grpc as grpcclient

    model = GatedModel(name="qos_fuse_trace")
    model.preferred_batch_sizes = [4]
    # Long gather window: the bucket must not dispatch until all four
    # mixed-priority requests are queued (it fills to preferred=4 and
    # dispatches immediately at that point).
    model.max_queue_delay_us = 2_000_000
    model.priority_levels = 2
    model.default_priority_level = 2
    core.repository.add_model(model)
    trace_file = str(tmp_path / "qos_trace.jsonl")
    core.trace_setting(model.name, {
        "trace_level": ["TIMESTAMPS"], "trace_rate": ["1"],
        "trace_count": ["-1"], "log_frequency": ["1"],
        "trace_file": [trace_file]})
    client = grpcclient.InferenceServerClient(grpc_handle.address)

    def send(value, priority):
        inputs = [grpcclient.InferInput("IN", [1, 4], "FP32")]
        inputs[0].set_data_from_numpy(
            np.full((1, 4), float(value), np.float32))
        client.infer(model.name, inputs, priority=priority)

    threads = [threading.Thread(target=send, args=(i, 1 + i % 2),
                                daemon=True) for i in range(4)]
    for thread in threads:
        thread.start()
    deadline = time.monotonic() + 5
    while core.model_statistics(model.name).model_stats[0] \
            .pipeline_stats.pending_count < 4 \
            and time.monotonic() < deadline:
        time.sleep(0.02)
    model.gate.set()
    for thread in threads:
        thread.join(timeout=15)
    client.close()
    core.trace_setting(model.name, {"trace_level": ["OFF"]})
    assert len(model.executions) == 1  # fused despite mixed classes
    records = [json.loads(line)
               for line in open(trace_file) if line.strip()]
    assert len(records) == 4
    batch_ids = set()
    priorities = []
    for record in records:
        for span in record["spans"]:
            if span["name"] == "batch_execute":
                batch_ids.add(span["span_id"])
            if span["name"] == "queue" \
                    and "priority" in (span.get("attrs") or {}):
                priorities.append(span["attrs"]["priority"])
    assert len(batch_ids) == 1  # ONE shared fused-execution span
    assert sorted(priorities) == [1, 1, 2, 2]


def test_tenant_quota_http_429_retry_after_and_recovery(qos_servers):
    import client_tpu.http as httpclient
    from client_tpu.robust import RetryPolicy

    core, _, http_runner = qos_servers
    with httpclient.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port) as client:
        params = {"tenant": "limited"}
        client.infer("simple_qos", _qos_inputs(httpclient),
                     parameters=params)  # burst of 1 spent
        with pytest.raises(InferenceServerException) as excinfo:
            client.infer("simple_qos", _qos_inputs(httpclient),
                         parameters=params)
        error = excinfo.value
        assert error.status() == "429"
        # rate 2/s -> ~0.5s to the next token, rounded up to integer
        # delta-seconds for the HTTP header (RFC 9110)
        assert getattr(error, "retry_after_s", None) == 1.0
        # the PR-2 retry policy recovers by honoring the advised pacing
        policy = RetryPolicy(max_attempts=4, initial_backoff_s=0.01)
        attempts = [0]

        def call():
            attempts[0] += 1
            return client.infer("simple_qos", _qos_inputs(httpclient),
                                parameters=params)

        from client_tpu.robust import call_with_retry

        call_with_retry(lambda _r: call(), policy)
        assert attempts[0] >= 1
    stats = core.model_statistics("simple_qos").model_stats[0]
    rows = {r.tenant: r for r in stats.tenant_stats}
    assert rows["limited"].reject_count >= 1
    assert rows["limited"].success_count >= 2


def test_tenant_quota_grpc_resource_exhausted_with_retry_after(
        qos_servers):
    import client_tpu.grpc as grpcclient

    core, grpc_handle, _ = qos_servers
    with grpcclient.InferenceServerClient(grpc_handle.address) as client:
        params = {"tenant": "limited"}
        statuses = []
        error = None
        for _ in range(4):
            try:
                client.infer("simple_qos", _qos_inputs(grpcclient),
                             parameters=params)
                statuses.append("ok")
            except InferenceServerException as e:
                statuses.append(e.status())
                error = e
        assert "RESOURCE_EXHAUSTED" in statuses
        # retry-after trailing metadata parsed into the exception
        assert getattr(error, "retry_after_s", 0) > 0


def test_tenant_identity_from_header_and_metadata(qos_servers):
    import urllib.request

    import grpc as grpc_mod

    import client_tpu.http as httpclient
    from client_tpu.protocol import inference_pb2 as pb
    from client_tpu.protocol.service import GRPCInferenceServiceStub

    core, grpc_handle, http_runner = qos_servers

    def tenant_rows():
        stats = core.model_statistics("simple_qos").model_stats[0]
        return {r.tenant: int(r.success_count)
                for r in stats.tenant_stats}

    before = tenant_rows()
    # HTTP: x-tenant-id header maps onto the tenant parameter
    body, json_len = httpclient.InferenceServerClient. \
        generate_request_body(_qos_inputs(httpclient))
    request = urllib.request.Request(
        "http://127.0.0.1:%d/v2/models/simple_qos/infer"
        % http_runner.port, data=body,
        headers={"x-tenant-id": "header-co",
                 "Inference-Header-Content-Length": str(json_len)})
    with urllib.request.urlopen(request) as response:
        assert response.status == 200
    # gRPC: `tenant` invocation metadata key
    channel = grpc_mod.insecure_channel(grpc_handle.address)
    stub = GRPCInferenceServiceStub(channel)
    infer_request = pb.ModelInferRequest(model_name="simple_qos")
    for name in ("INPUT0", "INPUT1"):
        tensor = infer_request.inputs.add()
        tensor.name = name
        tensor.datatype = "INT32"
        tensor.shape.extend([1, 16])
        infer_request.raw_input_contents.append(
            np.arange(16, dtype=np.int32)[None].tobytes())
    stub.ModelInfer(infer_request, metadata=(("tenant", "meta-co"),))
    channel.close()
    after = tenant_rows()
    assert after.get("header-co", 0) - before.get("header-co", 0) == 1
    assert after.get("meta-co", 0) - before.get("meta-co", 0) == 1


def test_decoupled_stream_respects_tenant_quota(qos_servers):
    """The streaming path must not bypass admission: a decoupled
    stream spends one quota token and holds one in-flight slot for
    its duration."""
    from client_tpu.protocol import inference_pb2 as pb

    core, _, _ = qos_servers
    core.repository.load("repeat_int32")

    def stream_request():
        request = pb.ModelInferRequest(model_name="repeat_int32")
        tensor = request.inputs.add()
        tensor.name = "IN"
        tensor.datatype = "INT32"
        tensor.shape.extend([2])
        request.raw_input_contents.append(
            np.array([1, 2], np.int32).tobytes())
        request.parameters["tenant"].string_param = "streamlim"
        return request

    responses = list(core.stream_infer(stream_request()))
    assert any(not r.error_message for r in responses)
    # burst of 1 spent, refill 0.2/s: the next stream is rejected
    with pytest.raises(InferenceServerException) as excinfo:
        list(core.stream_infer(stream_request()))
    assert excinfo.value.status() == "RESOURCE_EXHAUSTED"
    assert excinfo.value.retry_after_s > 0
    snap = core.tenant_quotas.snapshot()["streamlim"]
    assert snap["admitted"] == 1
    assert snap["rejected"] == 1
    assert snap["inflight"] == 0  # released when the stream completed


def test_decoupled_stream_releases_quota_when_acquire_fails(qos_servers):
    """Regression: a failure BETWEEN quota admission and stream start
    (model draining -> repository.acquire raises) must still return
    the tenant's token and in-flight slot, or a concurrency-capped
    tenant is starved forever after `cap` such failures."""
    from client_tpu.protocol import inference_pb2 as pb
    from client_tpu.server.qos import TenantQuotaManager

    core, _, _ = qos_servers
    core.repository.load("repeat_int32")

    def stream_request():
        request = pb.ModelInferRequest(model_name="repeat_int32")
        tensor = request.inputs.add()
        tensor.name = "IN"
        tensor.datatype = "INT32"
        tensor.shape.extend([2])
        request.raw_input_contents.append(
            np.array([1, 2], np.int32).tobytes())
        request.parameters["tenant"].string_param = "capped"
        return request

    saved_quotas = core.tenant_quotas
    saved_acquire = core.repository.acquire
    try:
        core.tenant_quotas = TenantQuotaManager.from_spec(
            "default=rate:10000;capped=concurrency:2")

        def draining_acquire(name, version=""):
            raise InferenceServerException(
                "model '%s' is draining" % name, status="UNAVAILABLE")

        core.repository.acquire = draining_acquire
        for _ in range(3):  # > concurrency cap
            with pytest.raises(InferenceServerException) as excinfo:
                list(core.stream_infer(stream_request()))
            # the drain error, never a quota reject from leaked slots
            assert excinfo.value.status() == "UNAVAILABLE"
        snap = core.tenant_quotas.snapshot()["capped"]
        assert snap["inflight"] == 0
        # recovery: acquire works again -> the tenant streams normally
        core.repository.acquire = saved_acquire
        responses = list(core.stream_infer(stream_request()))
        assert any(not r.error_message for r in responses)
        assert core.tenant_quotas.snapshot()["capped"]["inflight"] == 0
    finally:
        core.repository.acquire = saved_acquire
        core.tenant_quotas = saved_quotas


def test_untagged_requests_account_as_anonymous(qos_servers):
    import client_tpu.http as httpclient

    core, _, http_runner = qos_servers
    with httpclient.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port) as client:
        client.infer("simple_qos", _qos_inputs(httpclient))
    stats = core.model_statistics("simple_qos").model_stats[0]
    rows = {r.tenant for r in stats.tenant_stats}
    assert ANONYMOUS_TENANT in rows


def test_qos_prometheus_families(qos_servers):
    core, _, _ = qos_servers
    text = core.metrics_text()
    assert "tpu_tenant_success_total{" in text
    assert "tpu_tenant_rejected_total{" in text
    assert 'tpu_shed_total{model="simple_qos",priority="' in text
    assert "tpu_tenant_tokens{" in text
    # priority queue gauge appears once the batcher exists
    assert "tpu_priority_queue_size" in text


def test_tenant_label_values_escaped_in_metrics(qos_servers):
    """Tenant is the one client-supplied Prometheus label value: a
    quote/backslash/newline in it must not corrupt the exposition."""
    import client_tpu.http as httpclient

    core, _, http_runner = qos_servers
    hostile = 'evil"} 1\ninjected{x="'
    with httpclient.InferenceServerClient(
            "127.0.0.1:%d" % http_runner.port) as client:
        client.infer("simple_qos", _qos_inputs(httpclient),
                     parameters={"tenant": hostile})
    text = core.metrics_text()
    assert 'tenant="evil\\"} 1\\ninjected{x=\\""' in text
    import re
    for line in text.splitlines():  # every sample line stays one line
        if "evil" in line:
            assert re.fullmatch(
                r'[a-zA-Z_][a-zA-Z0-9_]*\{tenant=".*"\} [0-9.+-eE]+',
                line), line


def test_higher_priority_miss_does_not_coalesce_behind_bulk_leader():
    """Cache x QoS interplay: priority is excluded from the cache key,
    so an identical higher-class arrival WOULD coalesce onto a bulk
    leader and inherit its back-of-queue wait — exactly the saturation
    condition priority dispatch exists for. It must execute
    independently instead; same-class arrivals still coalesce."""
    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import InferResult, get_inference_request
    from client_tpu.models.add_sub import AddSub
    from client_tpu.server.app import build_core

    release = threading.Event()
    entered = threading.Event()

    class GatedQoSCache(AddSub):
        response_cache = True

        def __init__(self):
            super().__init__(name="qos_cache", datatype="INT32",
                             shape=(16,))
            self.priority_levels = 2
            self.default_priority_level = 2
            self.calls = 0

        def infer(self, inputs, parameters=None):
            self.calls += 1
            if self.calls == 1:  # hold the bulk leader mid-execution
                entered.set()
                assert release.wait(5)
            return super().infer(inputs, parameters)

    core = build_core([], warmup=False)
    model = GatedQoSCache()
    core.repository.add_model(model)

    def request(priority=0):
        tensors = []
        for name, fill in (("INPUT0", 3), ("INPUT1", 6)):
            tensor = InferInput(name, [16], "INT32")
            tensor.set_data_from_numpy(np.full((16,), fill, np.int32))
            tensors.append(tensor)
        return get_inference_request(
            model_name="qos_cache", inputs=tensors, outputs=None,
            priority=priority)

    try:
        leader_results = []
        leader = threading.Thread(
            target=lambda: leader_results.append(core.infer(request())))
        leader.start()
        try:
            assert entered.wait(5)
            # identical content, higher class: completes while the
            # bulk leader is still held, via its own execution
            response = core.infer(request(priority=1))
            value = int(InferResult(response)
                        .as_numpy("OUTPUT0").reshape(-1)[0])
            assert value == 9
            assert model.calls == 2
            assert leader.is_alive()  # overtake never woke the leader
        finally:
            release.set()
            leader.join(timeout=5)
        assert len(leader_results) == 1
        # the leader resolved + inserted: a same-class repeat is a hit
        core.infer(request())
        assert model.calls == 2
    finally:
        core.shutdown()


# -- overload chaos scenario ----------------------------------------------


def test_overload_scenario_spec_parsing():
    from client_tpu.server.chaos import OverloadScenario

    kwargs = OverloadScenario.parse_spec(
        "rate=500,after_s=1,duration_s=3,workers=4,seed=7")
    assert kwargs == {"rate": 500.0, "burst_after_s": 1.0,
                      "burst_duration_s": 3.0, "workers": 4, "seed": 7}
    with pytest.raises(ValueError):
        OverloadScenario.parse_spec("nope")
    with pytest.raises(ValueError):
        OverloadScenario.parse_spec("frobnicate=1")


def test_overload_scenario_counts_submissions_and_rejects():
    from client_tpu.server.chaos import OverloadScenario

    calls = []

    def submit():
        calls.append(1)
        if len(calls) % 2 == 0:
            raise InferenceServerException("shed", status="UNAVAILABLE")

    # one worker: the even/odd reject pattern in submit() is only
    # deterministic when calls are sequential
    scenario = OverloadScenario(submit, rate=0.0, burst_after_s=0.0,
                                burst_duration_s=0.3, workers=1).start()
    deadline = time.monotonic() + 5
    while not scenario.finished.is_set() \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    scenario.stop()
    stats = scenario.stats()
    assert stats["submitted"] == len(calls)
    assert stats["rejected"] == len(calls) // 2
    assert scenario.started.is_set()


def test_overload_scenario_stage_cancel():
    from client_tpu.server.chaos import OverloadScenario

    scenario = OverloadScenario(lambda: None, burst_after_s=30.0,
                                burst_duration_s=1.0).start()
    scenario.stop()  # cancels before the burst ever fires
    assert not scenario.started.is_set()
    assert scenario.stats()["submitted"] == 0


# -- perf harness QoS pieces ----------------------------------------------


def test_priority_mix_parse_and_schedule():
    from client_tpu.perf.load_manager import (
        build_priority_schedule,
        parse_priority_mix,
    )

    mix = parse_priority_mix("1:0.25,2:0.75")
    assert mix == [(1, 0.25), (2, 0.75)]
    assert parse_priority_mix("1,2") == [(1, 1.0), (2, 1.0)]
    with pytest.raises(ValueError):
        parse_priority_mix("")
    # levels start at 1: 0 would issue unclassed requests, negatives
    # would be rejected INVALID_ARGUMENT at the server mid-run
    with pytest.raises(ValueError):
        parse_priority_mix("0:1")
    with pytest.raises(ValueError):
        parse_priority_mix("-1:0.5,2:0.5")
    with pytest.raises(ValueError):
        parse_priority_mix("1:0")
    schedule = build_priority_schedule([(1, 1), (2, 3)], slots=8)
    assert schedule.count(1) == 2
    assert schedule.count(2) == 6
    # interleaved, not blocked: no run of four 2s containing all the 1s
    assert schedule[:4].count(2) < 4 or schedule[4:].count(1) == 0


def test_profiler_deltas_for_qos_stats():
    from client_tpu.perf.profiler import (
        _accumulate_server_stats,
        _delta_server_stats,
        _normalize_stats_entry,
    )

    before_entry = _normalize_stats_entry({
        "name": "m", "version": "1", "shed_count": "2",
        "priority_stats": [
            {"priority_level": "1", "success_count": "10",
             "queue_ns": "1000"}],
        "tenant_stats": [
            {"tenant": "a", "success_count": "5",
             "reject_count": "1"}],
    })
    after_entry = _normalize_stats_entry({
        "name": "m", "version": "1", "shed_count": "5",
        "priority_stats": [
            {"priority_level": "1", "success_count": "16",
             "queue_ns": "4000"},
            {"priority_level": "2", "success_count": "3",
             "shed_count": "3"}],
        "tenant_stats": [
            {"tenant": "a", "success_count": "9",
             "reject_count": "4"}],
    })
    delta = _delta_server_stats(
        {("m", "1"): before_entry}, {("m", "1"): after_entry})
    entry = delta["model_stats"][0]
    assert entry["shed_count"] == 3
    rows = {r["priority_level"]: r for r in entry["priority_stats"]}
    assert rows[1]["success_count"] == 6
    assert rows[1]["queue_ns"] == 3000
    assert rows[2]["shed_count"] == 3
    tenant_rows = {r["tenant"]: r for r in entry["tenant_stats"]}
    assert tenant_rows["a"]["success_count"] == 4
    assert tenant_rows["a"]["reject_count"] == 3
    # merging two stable windows sums the rows
    merged = _accumulate_server_stats(delta, delta)
    entry = merged["model_stats"][0]
    rows = {r["priority_level"]: r for r in entry["priority_stats"]}
    assert rows[1]["success_count"] == 12


# -- post-review hardening regressions ------------------------------------


def test_quota_reject_never_fails_over_in_pool():
    """A RESOURCE_EXHAUSTED quota reject is a policy signal enforced
    identically on every replica: failing over immediately would turn
    one throttled tenant's request into fleet-size physical hits and
    skip the Retry-After pacing. The pool path must back off (floored
    at Retry-After) instead of trying the next endpoint, and with no
    policy (pure failover) must surface the reject after ONE attempt."""
    from client_tpu.robust import (
        EndpointPool,
        RetryPolicy,
        call_with_retry_pool,
    )

    def reject(state, remaining):
        calls.append(state.url)
        error = InferenceServerException(
            "tenant over quota", status="RESOURCE_EXHAUSTED")
        error.retry_after_s = 0.2
        raise error

    # Pure failover (policy=None): one attempt, no fan-out.
    calls, pool = [], EndpointPool(
        ["a", "b"], hedge_max_ratio=0.0, explore_ratio=0.0)
    with pytest.raises(InferenceServerException) as err:
        call_with_retry_pool(reject, pool, None, sleep=lambda s: None)
    assert err.value.status() == "RESOURCE_EXHAUSTED"
    assert len(calls) == 1
    assert pool.stats()["failovers"] == 0

    # With a policy: the retry waits at least Retry-After; the second
    # attempt is a paced re-try, never counted as a failover.
    calls, slept = [], []
    pool = EndpointPool(["a", "b"], hedge_max_ratio=0.0,
                        explore_ratio=0.0)
    with pytest.raises(InferenceServerException):
        call_with_retry_pool(
            reject, pool, RetryPolicy(max_attempts=2),
            sleep=slept.append)
    assert len(calls) == 2
    assert slept and slept[0] >= 0.2
    assert pool.stats()["failovers"] == 0


def test_cache_hit_and_follower_success_labeled_per_priority():
    """priority_stats must count cache-hit successes: with
    response_cache + priority_levels both on, a class fully served
    from cache would otherwise report ~0 per-class goodput while
    inference_count says every request succeeded."""
    from client_tpu._infer_common import InferInput
    from client_tpu.models.add_sub import AddSub
    from client_tpu.grpc._utils import get_inference_request
    from client_tpu.server.app import build_core

    class QoSCache(AddSub):
        response_cache = True

        def __init__(self):
            super().__init__(name="qos_cache_stats", datatype="INT32",
                             shape=(16,))
            self.priority_levels = 2
            self.default_priority_level = 2

    core = build_core([], warmup=False)
    core.repository.add_model(QoSCache())

    def request():
        tensors = []
        for name, fill in (("INPUT0", 3), ("INPUT1", 6)):
            tensor = InferInput(name, [16], "INT32")
            tensor.set_data_from_numpy(np.full((16,), fill, np.int32))
            tensors.append(tensor)
        return get_inference_request(
            model_name="qos_cache_stats", inputs=tensors, outputs=None,
            priority=1)

    try:
        core.infer(request())  # miss: executes, labeled by the batcher
        core.infer(request())  # identical repeat: served from cache
        hist = core._stats_for("qos_cache_stats").priority_hist
        assert hist[1][0] == 2  # both successes land in class 1
    finally:
        core.shutdown()


def test_hook_body_typeerror_is_not_reinvoked():
    """_hook decides arity by signature, not by catching TypeError
    from the call: a hook whose BODY raises TypeError must not be
    silently re-run (its side effects would double-count)."""
    calls = []

    def broken(priority):
        calls.append(priority)
        raise TypeError("internal bug, not an arity mismatch")

    DynamicBatcher._hook(broken, 1)
    assert calls == [1]  # swallowed once, never re-invoked zero-arg

    legacy_calls = []
    DynamicBatcher._hook(lambda: legacy_calls.append(1), 2)
    assert legacy_calls == [1]  # pre-QoS zero-arg hooks still work
