"""Standalone ctypes DLPack layer (parity: reference utils/_dlpack.py
— framework-free tensor ingestion)."""

import numpy as np
import pytest

from client_tpu.utils import _dlpack


def test_numpy_roundtrip_zero_copy():
    source = np.arange(12, dtype=np.float32).reshape(3, 4)
    view = _dlpack.capsule_to_numpy(source.__dlpack__())
    np.testing.assert_array_equal(view, source)
    # Zero copy: mutating the source shows through the view.
    source[0, 0] = 99.0
    assert view[0, 0] == 99.0


def test_dtypes_roundtrip():
    for dtype in (np.int8, np.int16, np.int32, np.int64, np.uint8,
                  np.uint16, np.uint32, np.uint64, np.float16,
                  np.float32, np.float64, np.bool_):
        source = np.zeros(5, dtype=dtype)
        view = _dlpack.to_numpy(_Wrapper(source))
        assert view.dtype == source.dtype
        np.testing.assert_array_equal(view, source)


class _Wrapper:
    """A minimal producer exposing only __dlpack__."""

    def __init__(self, array):
        self._array = array

    def __dlpack__(self, stream=None):
        return self._array.__dlpack__()


def test_device_query():
    source = np.zeros(3)
    device = _dlpack.get_dlpack_device(source)
    assert device[0] == _dlpack.DLDeviceType.kDLCPU


def test_torch_tensor_ingestion():
    torch = pytest.importorskip("torch")
    tensor = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    view = _dlpack.to_numpy(tensor)
    np.testing.assert_array_equal(view, tensor.numpy())


def test_jax_cpu_array_ingestion():
    import jax.numpy as jnp

    array = jnp.arange(8, dtype=jnp.int32)
    view = _dlpack.to_numpy(array)
    np.testing.assert_array_equal(view, np.arange(8, dtype=np.int32))


def test_non_contiguous_rejected():
    source = np.arange(16, dtype=np.float32).reshape(4, 4)
    sliced = source[:, ::2]  # strided view
    with pytest.raises((ValueError, BufferError)):
        _dlpack.capsule_to_numpy(sliced.__dlpack__())


def test_used_capsule_rejected():
    source = np.zeros(4)
    capsule = source.__dlpack__()
    _dlpack.capsule_to_numpy(capsule)  # does not consume the name
    # Consuming via numpy marks it used; a second parse must fail.
    np.from_dlpack(_CapsuleCarrier(capsule))
    with pytest.raises(ValueError):
        _dlpack.get_managed_tensor(capsule)


class _CapsuleCarrier:
    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, stream=None, max_version=None):
        return self._capsule

    def __dlpack_device__(self):
        return (_dlpack.DLDeviceType.kDLCPU, 0)


def test_triton_to_dlpack_dtype():
    dt = _dlpack.triton_to_dlpack_dtype("FP32")
    assert (dt.type_code, dt.bits, dt.lanes) == (
        _dlpack.DLDataTypeCode.kDLFloat, 32, 1)
    with pytest.raises(ValueError):
        _dlpack.triton_to_dlpack_dtype("BYTES")


def test_bf16_dtype_mapping():
    import ml_dtypes

    dt = _dlpack.DLDataType(_dlpack.DLDataTypeCode.kDLBfloat, 16, 1)
    assert _dlpack.dlpack_to_np_dtype(dt) == np.dtype(ml_dtypes.bfloat16)


def test_zero_size_tensor():
    torch = pytest.importorskip("torch")
    empty = torch.empty(3, 0)
    view = _dlpack.to_numpy(empty)
    assert view.shape == (3, 0)
