"""genai layer: synthetic prompts, input datasets, profile-export
parsing, statistics, exporters, and the full CLI pipeline against the
in-process server (parity: genai-perf/tests)."""

import json
import os

import numpy as np
import pytest

from client_tpu.genai.exporters import console_report, export_csv, export_json
from client_tpu.genai.inputs import LlmInputs, OutputFormat
from client_tpu.genai.metrics import LLMProfileDataParser, Statistics
from client_tpu.genai.synthetic import SyntheticPromptGenerator
from client_tpu.genai.tokenizer import ByteLevelTokenizer, get_tokenizer
from client_tpu.genai.wrapper import Profiler

MS = 1_000_000  # ns per ms


def test_tokenizer_roundtrip():
    tok = get_tokenizer("byte")
    assert isinstance(tok, ByteLevelTokenizer)
    ids = tok.encode("hello")
    assert len(ids) == 5
    assert tok.decode(ids) == "hello"


@pytest.mark.slow  # probes the optional HF tokenizer import path
def test_tokenizer_unknown_raises():
    with pytest.raises(ValueError):
        get_tokenizer("definitely/not-a-model-on-disk")


def test_synthetic_prompt_token_count():
    tok = get_tokenizer("byte")
    gen = SyntheticPromptGenerator(tok, seed=3)
    prompt = gen.generate_prompt(mean_tokens=50)
    assert abs(len(tok.encode(prompt)) - 50) <= 12  # word granularity


def test_llm_inputs_dataset_format(tmp_path):
    tok = get_tokenizer("byte")
    inputs = LlmInputs(tok)
    prompts = inputs.create_prompts(num_prompts=3, input_tokens_mean=20)
    assert len(prompts) == 3
    dataset = inputs.convert_to_dataset(prompts, output_tokens_mean=8)
    assert len(dataset["data"]) == 3
    step = dataset["data"][0]
    assert step["max_tokens"] == [8]
    assert isinstance(step["text_input"][0], str)
    path = inputs.write_dataset(dataset, str(tmp_path / "in.json"))
    assert json.load(open(path))["data"]


def test_llm_inputs_from_file(tmp_path):
    f = tmp_path / "prompts.jsonl"
    f.write_text('{"text_input": "alpha"}\nplain beta\n')
    inputs = LlmInputs(get_tokenizer("byte"))
    prompts = inputs.create_prompts(num_prompts=0, input_file=str(f))
    assert prompts == ["alpha", "plain beta"]


def test_openai_chat_format():
    inputs = LlmInputs(get_tokenizer("byte"))
    dataset = inputs.convert_to_dataset(
        ["hi"], OutputFormat.OPENAI_CHAT, output_tokens_mean=4,
        model_name="m")
    payload = dataset["data"][0]["payload"][0]
    assert payload["messages"][0]["content"] == "hi"
    assert payload["stream"] is True


def _export_doc():
    """Two requests with known timings: TTFT 10ms/20ms, ITLs 5ms."""
    def req(start_ms, ttft_ms, n_tokens, itl_ms):
        start = start_ms * MS
        responses = [start + ttft_ms * MS]
        for _ in range(n_tokens - 1):
            responses.append(responses[-1] + itl_ms * MS)
        return {"timestamp": start, "response_timestamps": responses}

    return {
        "experiments": [{
            "experiment": {"mode": "concurrency", "value": 1},
            "requests": [req(0, 10, 4, 5), req(100, 20, 4, 5)],
        }],
    }


def test_profile_parser_metrics():
    parser = LLMProfileDataParser(document=_export_doc(),
                                  tokenizer=get_tokenizer("byte"))
    metrics = parser.get_metrics(0)
    assert [t / MS for t in metrics.time_to_first_token_ns] == [10, 20]
    assert all(t / MS == 5 for t in metrics.inter_token_latency_ns)
    assert len(metrics.inter_token_latency_ns) == 6
    assert metrics.output_token_counts == [4, 4]
    # duration: first start 0 -> last response (100 + 20 + 15)ms
    assert metrics.benchmark_duration_s == pytest.approx(0.135)
    assert metrics.output_token_throughput_per_s == pytest.approx(
        8 / 0.135)


def test_statistics_and_exporters(tmp_path):
    parser = LLMProfileDataParser(document=_export_doc(),
                                  tokenizer=get_tokenizer("byte"))
    stats = parser.get_statistics(0)
    d = stats.as_dict()
    assert d["time_to_first_token_ms"]["mean"] == pytest.approx(15.0)
    assert d["inter_token_latency_ms"]["p50"] == pytest.approx(5.0)
    assert "request_throughput_per_s" in d
    report = console_report(stats)
    assert "time_to_first_token_ms" in report
    export_json([stats], str(tmp_path / "out.json"), meta={"model": "m"})
    assert json.load(open(tmp_path / "out.json"))["experiments"]
    export_csv([stats], str(tmp_path / "out.csv"))
    assert "time_to_first_token_ms" in (tmp_path / "out.csv").read_text()


def test_wrapper_build_args():
    args = Profiler.build_args(model="llm_tiny", service_kind="inprocess",
                               concurrency=2, input_path="i.json",
                               export_path="e.json")
    assert "--streaming" in args
    assert "-u" not in args  # inprocess needs no url
    assert args[args.index("--concurrency-range") + 1] == "2"


@pytest.mark.slow  # full profiling run over the in-process backend
def test_genai_cli_e2e_inprocess(tmp_path):
    from client_tpu.genai.main import run
    from client_tpu.server.app import build_core

    core = build_core(["llm_tiny"])
    json_out = tmp_path / "stats.json"
    rc = run([
        "-m", "llm_tiny", "--service-kind", "inprocess",
        "--num-prompts", "3", "--output-tokens-mean", "4",
        "--synthetic-input-tokens-mean", "12",
        # count_windows holds each window open until 3 requests
        # complete (up to 10x the interval), so a contended CI box
        # cannot close a window empty-handed.
        "--measurement-mode", "count_windows",
        "--measurement-request-count", "3",
        "--measurement-interval", "2000", "--max-trials", "2",
        "--stability-percentage", "90",
        "--artifact-dir", str(tmp_path),
        "--export-json", str(json_out),
    ], core=core)
    assert rc == 0
    doc = json.loads(json_out.read_text())
    exp = doc["experiments"][0]
    assert "time_to_first_token_ms" in exp
    assert exp["output_token_throughput_per_s"]["value"] > 0


@pytest.mark.slow  # full profiling run over the OpenAI SSE backend
def test_genai_cli_e2e_openai(tmp_path):
    """genai over the OpenAI-compatible endpoint: SSE chunks become
    TTFT / inter-token metrics (parity: genai-perf's openai
    endpoint-format path)."""
    from client_tpu.genai.main import run
    from client_tpu.server.app import build_core
    from client_tpu.server.http_server import start_http_server_thread

    core = build_core(["llm_tiny"])
    runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    json_out = tmp_path / "stats.json"
    try:
        rc = run([
            "-m", "llm_tiny", "--service-kind", "openai",
            "-u", "127.0.0.1:%d" % runner.port,
            "--endpoint", "v1/chat/completions",
            "--num-prompts", "3", "--output-tokens-mean", "4",
            "--synthetic-input-tokens-mean", "12",
            "--measurement-mode", "count_windows",
            "--measurement-request-count", "3",
            "--measurement-interval", "2000", "--max-trials", "2",
            "--stability-percentage", "90",
            "--artifact-dir", str(tmp_path),
            "--export-json", str(json_out),
        ])
    finally:
        runner.stop()
    assert rc == 0
    doc = json.loads(json_out.read_text())
    exp = doc["experiments"][0]
    assert "time_to_first_token_ms" in exp
    assert "inter_token_latency_ms" in exp


def test_export_parquet(tmp_path):
    import pandas as pd

    parser = LLMProfileDataParser(document=_export_doc(),
                                  tokenizer=get_tokenizer("byte"))
    stats = parser.get_statistics(0)
    from client_tpu.genai.exporters import export_parquet

    path = tmp_path / "out.parquet"
    export_parquet([stats], str(path))
    frame = pd.read_parquet(path)
    assert set(frame.columns) == {"experiment", "metric", "sample_index",
                                  "value"}
    ttft = frame[frame.metric == "time_to_first_token_ms"]
    assert list(ttft.value) == [10.0, 20.0]
    assert (frame[frame.metric == "request_throughput_per_s"].value > 0).all()


def test_generate_plots(tmp_path):
    parser = LLMProfileDataParser(document=_export_doc(),
                                  tokenizer=get_tokenizer("byte"))
    stats = parser.get_statistics(0)
    from client_tpu.genai.plots import generate_plots

    written = generate_plots([stats], str(tmp_path), title="t")
    names = {os.path.basename(p) for p in written}
    assert names == {
        "time_to_first_token.png", "inter_token_latency.png",
        "request_latency.png", "token_position_heatmap.png",
        "experiment_comparison.png",
    }
    for path in written:
        assert os.path.getsize(path) > 1000  # a real PNG, not a stub


def test_generate_plots_multi_experiment_comparison(tmp_path):
    """Two experiments render the comparison + heatmap set (parity:
    genai-perf's cross-experiment plot suite)."""
    doc = _export_doc()
    doc["experiments"].append(doc["experiments"][0])
    parser = LLMProfileDataParser(document=doc,
                                  tokenizer=get_tokenizer("byte"))
    from client_tpu.genai.plots import generate_plots

    stats = [parser.get_statistics(0), parser.get_statistics(1)]
    written = generate_plots(stats, str(tmp_path), title="sweep")
    names = {os.path.basename(p) for p in written}
    assert "experiment_comparison.png" in names
    assert "token_position_heatmap.png" in names


def test_generate_html_report(tmp_path):
    """The interactive report (parity: genai-perf's plotly HTML) is one
    self-contained file: every chart, the hover layer, and a table view
    with no external resources."""
    doc = _export_doc()
    doc["experiments"].append(doc["experiments"][0])
    parser = LLMProfileDataParser(document=doc,
                                  tokenizer=get_tokenizer("byte"))
    from client_tpu.genai.html_report import generate_html_report

    stats = [parser.get_statistics(0), parser.get_statistics(1)]
    path = generate_html_report(stats, str(tmp_path), title="sweep")
    text = open(path).read()
    assert os.path.basename(path) == "report.html"
    # all chart sections present
    for heading in ("Time to first token", "Request latency",
                    "Inter-token latency", "token position",
                    "Summary table"):
        assert heading in text
    # interactivity: per-mark tooltips + the hover script
    assert text.count("data-tip=") > 4
    assert "mousemove" in text
    # >=2 series: legend present; identity never color-alone
    assert "experiment 0" in text and "experiment 1" in text
    # self-contained: no external fetches of any kind
    assert "http://" not in text and "https://" not in text
    # dark mode is selected, not an automatic flip
    assert "prefers-color-scheme: dark" in text


def test_html_report_single_series_has_no_legend(tmp_path):
    parser = LLMProfileDataParser(document=_export_doc(),
                                  tokenizer=get_tokenizer("byte"))
    from client_tpu.genai.html_report import generate_html_report

    path = generate_html_report([parser.get_statistics(0)], str(tmp_path))
    text = open(path).read()
    assert '<div class="legend">' not in text  # title names the series


def test_dataset_prompts_fetch_and_fallback():
    import io

    from client_tpu.genai.datasets import dataset_prompts
    from client_tpu.genai.synthetic import SyntheticPromptGenerator

    # Mocked datasets-server response (the fetch path).
    doc = {"rows": [{"row": {"question": "q%d" % i}} for i in range(5)]}

    class _Response(io.StringIO):
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    def opener(url, timeout):
        assert "Open-Orca" in url
        return _Response(json.dumps(doc))

    prompts = dataset_prompts("openorca", 3, _opener=opener)
    assert prompts == ["q0", "q1", "q2"]

    # Offline: degrade to the synthetic generator.
    def failing_opener(url, timeout):
        raise OSError("no network")

    generator = SyntheticPromptGenerator(get_tokenizer("byte"), 0)
    prompts = dataset_prompts("openorca", 4,
                              fallback_generator=generator,
                              _opener=failing_opener)
    assert len(prompts) == 4

    with pytest.raises(ValueError):
        dataset_prompts("nope", 1)
