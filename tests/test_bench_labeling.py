"""The bench orchestrator's honest-labeling contract (VERDICT r04).

The r04 record shipped CPU throughput under TPU stage names with
TPU-anchored vs_baseline ratios intact — these tests pin the rule that
ANY CPU-measured stage is suffixed ``_cpu_fallback`` and stripped of
every TPU-anchored comparison field, in the whole-run-fallback path as
well as the partial-supplement path."""

import importlib.util
import pathlib

REPO = pathlib.Path(__file__).resolve().parents[1]

spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
bench = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench)


def test_cpu_fallback_strips_every_tpu_anchor():
    stage = {
        "throughput": 10.7, "p50_latency_us": 740000.0, "batch": 8,
        "vs_baseline": 0.0644, "baseline_src": "ref",
        "mfu_est": 0.0002, "mfu_device": 0.04, "mfu_serving": 1e-5,
        "model_exec_ms": 210.0, "model_exec_ms_device": 1.5,
        "resnet50_model_exec_ms_device": 1.5,
        "relay_fetch_ms_est": 65.0, "resnet50_relay_fetch_ms_est": 65.0,
        "itl_p99_improvement": 1.2, "fusion_ratio": 0.2,
    }
    out = bench.as_cpu_fallback(stage)
    assert out["throughput"] == 10.7
    assert out["fusion_ratio"] == 0.2           # platform-neutral: kept
    assert out["model_exec_ms"] == 210.0        # raw probe: kept
    for key in out:
        assert not key.startswith(("vs_", "baseline_"))
        assert "mfu" not in key and "relay_fetch" not in key
        assert not key.endswith("_device")
        assert key != "itl_p99_improvement"


def test_merge_never_overwrites_real_platform_stage():
    result = {"stages": {"simple_grpc": {"throughput": 5000.0,
                                         "vs_baseline": 3.5}}}
    bench.merge_cpu_stages(result, {
        "simple_grpc": {"throughput": 9000.0, "vs_baseline": 6.4},
        "bert_grpc_sysshm": {"throughput": 5.0, "vs_baseline": 0.05},
    })
    assert result["stages"]["simple_grpc"]["throughput"] == 5000.0
    assert "simple_grpc_cpu_fallback" not in result["stages"]
    bert = result["stages"]["bert_grpc_sysshm_cpu_fallback"]
    assert bert == {"throughput": 5.0}
    assert "bert_grpc_sysshm" not in result["stages"]


def test_merge_keeps_host_placed_stages_whole():
    # `simple` is host-placed numpy: a CPU-platform measurement of it
    # is identical to a TPU-platform one, so it keeps its name AND its
    # reference anchor even in whole-run fallback mode.
    result = {"stages": {}}
    bench.merge_cpu_stages(result, {
        "simple_grpc": {"throughput": 1400.0, "vs_baseline": 1.0},
        "simple_inprocess_native": {"throughput": 9000.0,
                                    "vs_baseline": 459.0},
        "resnet50_tpu_shm_grpc": {"throughput": 10.0, "vs_baseline": 0.06,
                                  "mfu_device": 0.04},
    })
    assert result["stages"]["simple_grpc"]["vs_baseline"] == 1.0
    assert result["stages"]["simple_inprocess_native"]["throughput"] == 9000.0
    resnet = result["stages"]["resnet50_tpu_shm_grpc_cpu_fallback"]
    assert resnet == {"throughput": 10.0}


def test_tpu_stages_missing_targets_model_bound_stages():
    result = {"stages": {"simple_grpc": {}, "simple_inprocess": {},
                         "resnet50_tpu_shm_grpc": {}}}
    missing = bench.tpu_stages_missing(result)
    assert "bert_grpc_sysshm" in missing
    assert "llm_generate_stream" in missing
    assert "resnet50_tpu_shm_grpc" not in missing
    assert bench.tpu_stages_missing({"stages": {
        name: {} for name in ("resnet50_tpu_shm_grpc", "resnet50_inprocess",
                              "bert_grpc_sysshm", "ensemble_stream_grpc",
                              "llm_generate_stream")}}) == []


def test_flops_estimates_are_modeled():
    from client_tpu.models.bert import BertConfig, BertModel
    from client_tpu.models.resnet import ResNetModel
    from client_tpu.server.model import ServedModel

    assert ServedModel().flops_estimate(8) is None
    resnet = ResNetModel.__new__(ResNetModel)  # no param init needed
    assert resnet.flops_estimate(8) == 8 * 7.7e9
    bert = BertModel.__new__(BertModel)
    bert.cfg = BertConfig()
    # batch 32, seq 128, BERT-base: ~22.4 GFLOP/seq -> ~0.72 TFLOP.
    flops = bert.flops_estimate(32, 128)
    assert 0.5e12 < flops < 1.0e12
    # attention term grows quadratically with seq
    assert bert.flops_estimate(32, 256) > 2 * flops * 0.9
