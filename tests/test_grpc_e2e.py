"""End-to-end gRPC integration tests: real client against a real
in-process server with the `simple` add_sub model (tier-2 of the test
strategy, SURVEY.md §4 — the analogue of cc_client_test.cc run against
a live server)."""

import queue

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
from client_tpu.server.app import start_grpc_server
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def server():
    handle = start_grpc_server(
        load_models=["simple", "add_sub_fp32", "add_sub_large"])
    yield handle
    handle.stop()


@pytest.fixture()
def client(server):
    with grpcclient.InferenceServerClient(server.address) as c:
        yield c


def _simple_inputs():
    in0 = np.arange(16, dtype=np.int32)
    in1 = np.ones(16, dtype=np.int32)
    inputs = [
        grpcclient.InferInput("INPUT0", [16], "INT32"),
        grpcclient.InferInput("INPUT1", [16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(in0)
    inputs[1].set_data_from_numpy(in1)
    return in0, in1, inputs


def test_health(client):
    assert client.is_server_live()
    assert client.is_server_ready()
    assert client.is_model_ready("simple")
    assert not client.is_model_ready("no_such_model")


def test_server_metadata(client):
    meta = client.get_server_metadata()
    assert meta.name == "client_tpu_server"
    assert "system_shared_memory" in meta.extensions
    as_json = client.get_server_metadata(as_json=True)
    assert as_json["name"] == "client_tpu_server"


def test_model_metadata(client):
    meta = client.get_model_metadata("simple")
    assert meta.name == "simple"
    assert [t.name for t in meta.inputs] == ["INPUT0", "INPUT1"]
    assert list(meta.inputs[0].shape) == [16]
    assert meta.inputs[0].datatype == "INT32"


def test_model_config(client):
    config = client.get_model_config("simple")
    assert config.config.name == "simple"
    assert len(config.config.input) == 2


def test_model_metadata_unknown(client):
    with pytest.raises(InferenceServerException) as exc:
        client.get_model_metadata("no_such_model")
    assert exc.value.status() == "NOT_FOUND"


def test_infer(client):
    in0, in1, inputs = _simple_inputs()
    result = client.infer("simple", inputs)
    np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
    np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), in0 - in1)


def test_infer_requested_output_subset(client):
    in0, in1, inputs = _simple_inputs()
    outputs = [grpcclient.InferRequestedOutput("OUTPUT1")]
    result = client.infer("simple", inputs, outputs=outputs, request_id="42")
    assert result.get_response().id == "42"
    assert result.as_numpy("OUTPUT0") is None
    np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), in0 - in1)


def test_infer_fp32(client):
    x = np.random.rand(16).astype(np.float32)
    y = np.random.rand(16).astype(np.float32)
    inputs = [
        grpcclient.InferInput("INPUT0", [16], "FP32").set_data_from_numpy(x),
        grpcclient.InferInput("INPUT1", [16], "FP32").set_data_from_numpy(y),
    ]
    result = client.infer("add_sub_fp32", inputs)
    np.testing.assert_allclose(result.as_numpy("OUTPUT0"), x + y, rtol=1e-6)


def test_infer_multi_megabyte_tensors(client):
    """4 MiB per tensor through the Python client+server pair: both
    ends configure unlimited gRPC message sizes (grpcio's 4 MB default
    would reject the 8 MiB request), and values survive intact."""
    n = 1 << 20
    x = (np.arange(n, dtype=np.float32) % 9973)
    y = (np.arange(n, dtype=np.float32) % 7919)
    inputs = [
        grpcclient.InferInput("INPUT0", [n], "FP32").set_data_from_numpy(x),
        grpcclient.InferInput("INPUT1", [n], "FP32").set_data_from_numpy(y),
    ]
    result = client.infer("add_sub_large", inputs)
    np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), x + y)
    np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), x - y)


def test_infer_wrong_input_name(client):
    bad = grpcclient.InferInput("NOPE", [16], "INT32").set_data_from_numpy(
        np.zeros(16, dtype=np.int32)
    )
    _, _, inputs = _simple_inputs()
    with pytest.raises(InferenceServerException) as exc:
        client.infer("simple", [bad, inputs[1]])
    assert exc.value.status() == "INVALID_ARGUMENT"


def test_async_infer(client):
    in0, in1, inputs = _simple_inputs()
    results = queue.Queue()
    ctx = client.async_infer(
        "simple", inputs, lambda result, error: results.put((result, error))
    )
    result, error = results.get(timeout=10)
    assert error is None
    np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
    assert ctx is not None


def test_async_infer_error(client):
    _, _, inputs = _simple_inputs()
    results = queue.Queue()
    client.async_infer(
        "no_such_model", inputs, lambda r, e: results.put((r, e))
    )
    result, error = results.get(timeout=10)
    assert result is None
    assert isinstance(error, InferenceServerException)
    assert error.status() == "NOT_FOUND"


def test_stream_infer_non_decoupled(client):
    in0, in1, inputs = _simple_inputs()
    results = queue.Queue()
    client.start_stream(lambda result, error: results.put((result, error)))
    try:
        client.async_stream_infer("simple", inputs, request_id="s1")
        result, error = results.get(timeout=10)
        assert error is None
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
        params = result.get_parameters()
        assert params.get("triton_final_response") is True
    finally:
        client.stop_stream()


def test_statistics(client):
    in0, in1, inputs = _simple_inputs()
    client.infer("simple", inputs)
    stats = client.get_inference_statistics("simple")
    stat = stats.model_stats[0]
    assert stat.name == "simple"
    assert stat.inference_count >= 1
    assert stat.inference_stats.success.count >= 1
    assert stat.inference_stats.compute_infer.ns > 0


def test_repository_index_load_unload(client):
    index = client.get_model_repository_index()
    names = {m.name: m.state for m in index.models}
    assert names.get("simple") == "READY"
    assert "add_sub" in names
    client.load_model("add_sub")
    assert client.is_model_ready("add_sub")
    client.unload_model("add_sub")
    assert not client.is_model_ready("add_sub")


def test_trace_and_log_settings(client):
    settings = client.update_trace_settings(
        settings={"trace_level": ["TIMESTAMPS"], "trace_rate": "5"}
    )
    got = client.get_trace_settings()
    assert got.settings["trace_level"].value == ["TIMESTAMPS"]
    assert got.settings["trace_rate"].value == ["5"]
    log = client.update_log_settings({"log_verbose_level": 2})
    assert log.settings["log_verbose_level"].uint32_param == 2
    # reset: a global TIMESTAMPS level would trace later tests' infers
    client.update_trace_settings(settings={"trace_level": ["OFF"]})


def test_trace_records_written(client, tmp_path):
    """trace_level != OFF emits Triton-style timeline records to
    trace_file, honoring trace_count caps and monotonic timestamps."""
    import json as jsonlib

    trace_file = tmp_path / "trace.jsonl"
    client.update_trace_settings(
        model_name="simple",
        settings={"trace_level": ["TIMESTAMPS"], "trace_rate": "1",
                  "trace_count": "3", "log_frequency": "1",
                  "trace_file": str(trace_file)})
    try:
        in0, in1, inputs = _simple_inputs()
        for _ in range(5):
            client.infer("simple", inputs)
        lines = trace_file.read_text().strip().splitlines()
        assert len(lines) == 3  # trace_count caps emission
        record = jsonlib.loads(lines[0])
        assert record["model_name"] == "simple"
        names = [t["name"] for t in record["timestamps"]]
        assert names == ["REQUEST_START", "QUEUE_START", "COMPUTE_START",
                         "COMPUTE_END", "REQUEST_END"]
        stamps = [t["ns"] for t in record["timestamps"]]
        assert stamps == sorted(stamps)

        # Settings updates re-arm the counters (Triton semantics):
        # the same cap yields fresh records after an update.
        client.update_trace_settings(
            model_name="simple",
            settings={"trace_level": ["TIMESTAMPS"], "trace_rate": "1",
                      "trace_count": "2", "log_frequency": "1",
                      "trace_file": str(trace_file)})
        for _ in range(4):
            client.infer("simple", inputs)
        lines = trace_file.read_text().strip().splitlines()
        assert len(lines) == 5  # 3 from before + 2 re-armed
    finally:
        client.update_trace_settings(
            model_name="simple", settings={"trace_level": ["OFF"]})


def test_plugin_headers(server):
    seen = {}

    class Recorder(grpcclient.InferenceServerClientPlugin):
        def __call__(self, request):
            seen.update(request.headers)
            request.headers["x-extra"] = "1"

    with grpcclient.InferenceServerClient(server.address) as c:
        c.register_plugin(grpcclient.BasicAuth("user", "pass"))
        # chained: replace with recorder after unregistering
        c.unregister_plugin()
        c.register_plugin(Recorder())
        assert c.is_server_live()


def test_system_shared_memory_roundtrip(client):
    import client_tpu.utils.shared_memory as shm

    in0 = np.arange(16, dtype=np.int32)
    in1 = np.full(16, 2, dtype=np.int32)
    byte_size = in0.nbytes
    regions = []
    try:
        for name, arr in (("in0_region", in0), ("in1_region", in1)):
            handle = shm.create_shared_memory_region(name, "/ct_" + name,
                                                     byte_size)
            shm.set_shared_memory_region(handle, [arr])
            client.register_system_shared_memory(name, "/ct_" + name, byte_size)
            regions.append(handle)
        out_handle = shm.create_shared_memory_region(
            "out0_region", "/ct_out0", byte_size
        )
        regions.append(out_handle)
        client.register_system_shared_memory("out0_region", "/ct_out0",
                                             byte_size)

        status = client.get_system_shared_memory_status()
        assert set(status.regions.keys()) >= {"in0_region", "in1_region",
                                              "out0_region"}

        inputs = [
            grpcclient.InferInput("INPUT0", [16], "INT32"),
            grpcclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_shared_memory("in0_region", byte_size)
        inputs[1].set_shared_memory("in1_region", byte_size)
        outputs = [
            grpcclient.InferRequestedOutput("OUTPUT0"),
            grpcclient.InferRequestedOutput("OUTPUT1"),
        ]
        outputs[0].set_shared_memory("out0_region", byte_size)
        result = client.infer("simple", inputs, outputs=outputs)

        # OUTPUT0 landed in shared memory
        assert result.as_numpy("OUTPUT0") is None
        out_tensor = result.get_output("OUTPUT0")
        assert (
            out_tensor.parameters["shared_memory_region"].string_param
            == "out0_region"
        )
        out0 = shm.get_contents_as_numpy(out_handle, "INT32", [16])
        np.testing.assert_array_equal(out0, in0 + in1)
        # OUTPUT1 came back on the wire
        np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), in0 - in1)
    finally:
        client.unregister_system_shared_memory()
        for handle in regions:
            shm.destroy_shared_memory_region(handle)


def test_register_duplicate_region(client):
    import client_tpu.utils.shared_memory as shm

    handle = shm.create_shared_memory_region("dup", "/ct_dup", 64)
    try:
        client.register_system_shared_memory("dup", "/ct_dup", 64)
        with pytest.raises(InferenceServerException) as exc:
            client.register_system_shared_memory("dup", "/ct_dup", 64)
        assert exc.value.status() == "ALREADY_EXISTS"
    finally:
        client.unregister_system_shared_memory("dup")
        shm.destroy_shared_memory_region(handle)


def test_sync_server_fallback():
    """Both gRPC front-ends serve the same servicer: the asyncio
    transport is the default; aio=False keeps the classic thread-pool
    server working (also selectable via CLIENT_TPU_GRPC_AIO=0)."""
    handle = start_grpc_server(load_models=["simple"], aio=False)
    try:
        with grpcclient.InferenceServerClient(handle.address) as c:
            assert c.is_server_live()
            in0, in1, inputs = _simple_inputs()
            result = c.infer("simple", inputs)
            np.testing.assert_array_equal(result.as_numpy("OUTPUT0"),
                                          in0 + in1)
    finally:
        handle.stop()
