"""Unit tests for client_tpu.utils.shared_memory — both the native
libcshm.so backend and the pure-Python fallback (parity target:
reference test usage in src/python/library/tests and the
shared_memory.cc C extension surface)."""

import subprocess
import sys

import numpy as np
import pytest

import client_tpu.utils.shared_memory as shm


@pytest.fixture
def region():
    handle = shm.create_shared_memory_region("ut0", "/client_tpu_ut0", 1024)
    yield handle
    try:
        shm.destroy_shared_memory_region(handle)
    except shm.SharedMemoryException:
        pass


def test_native_backend_is_active():
    # g++ is in the image, so the C extension must have been built
    assert shm.using_native_backend()


def test_roundtrip_fp32(region):
    arr = np.arange(64, dtype=np.float32)
    shm.set_shared_memory_region(region, [arr])
    out = shm.get_contents_as_numpy(region, "FP32", (64,))
    assert np.array_equal(out, arr)


def test_roundtrip_bytes(region):
    arr = np.array([b"hello", b"tpu"], dtype=np.object_)
    shm.set_shared_memory_region(region, [arr])
    out = shm.get_contents_as_numpy(region, "BYTES", (2,))
    assert out[0] == b"hello" and out[1] == b"tpu"


def test_attach_sees_writes(region):
    arr = np.full(8, 7.5, dtype=np.float64)
    shm.set_shared_memory_region(region, [arr], offset=16)
    other = shm.attach_shared_memory_region("ut0b", "/client_tpu_ut0", 1024)
    try:
        out = shm.get_contents_as_numpy(other, np.float64, (8,), offset=16)
        assert np.array_equal(out, arr)
    finally:
        shm.detach_shared_memory_region(other)


def test_attach_missing_raises():
    with pytest.raises(shm.SharedMemoryException):
        shm.attach_shared_memory_region("nope", "/client_tpu_missing", 64)


def test_overflow_raises(region):
    with pytest.raises(shm.SharedMemoryException):
        shm.set_shared_memory_region(
            region, [np.zeros(4096, dtype=np.float32)])


def test_handle_info_and_registry(region):
    key, size, fd = shm.get_shared_memory_handle_info(region)
    assert key == "/client_tpu_ut0" and size == 1024 and fd >= 0
    assert "ut0" in shm.mapped_shared_memory_regions()


def test_cross_process_visibility(region):
    """Writes from another process are visible (the whole point of
    POSIX shm)."""
    arr = np.arange(10, dtype=np.int32)
    code = (
        "import numpy as np\n"
        "import client_tpu.utils.shared_memory as shm\n"
        "h = shm.attach_shared_memory_region('x', '/client_tpu_ut0', 1024)\n"
        "shm.set_shared_memory_region(h, [np.arange(10, dtype=np.int32)])\n"
        "shm.detach_shared_memory_region(h)\n"
    )
    subprocess.run([sys.executable, "-c", code], check=True, timeout=60)
    out = shm.get_contents_as_numpy(region, "INT32", (10,))
    assert np.array_equal(out, arr)


def test_python_fallback_roundtrip(monkeypatch):
    """The pure-Python path must keep working when libcshm is
    unavailable (CLIENT_TPU_NO_CSHM=1)."""
    code = (
        "import numpy as np\n"
        "import client_tpu.utils.shared_memory as shm\n"
        "assert not shm.using_native_backend()\n"
        "h = shm.create_shared_memory_region('f', '/client_tpu_fb', 256)\n"
        "shm.set_shared_memory_region(h, [np.arange(8, dtype=np.float32)])\n"
        "out = shm.get_contents_as_numpy(h, 'FP32', (8,))\n"
        "assert np.array_equal(out, np.arange(8, dtype=np.float32))\n"
        "shm.destroy_shared_memory_region(h)\n"
    )
    subprocess.run(
        [sys.executable, "-c", code], check=True, timeout=60,
        env={"CLIENT_TPU_NO_CSHM": "1", "PATH": "/usr/bin:/bin",
             "PYTHONPATH": "."},
    )


def test_views_survive_destroy():
    """Zero-copy views returned before destroy must stay readable —
    the native backend defers munmap until the views die."""
    h = shm.create_shared_memory_region("ut_v", "/client_tpu_utv", 256)
    arr = np.arange(32, dtype=np.float32)
    shm.set_shared_memory_region(h, [arr])
    out = shm.get_contents_as_numpy(h, "FP32", (32,))
    shm.destroy_shared_memory_region(h)
    assert np.array_equal(out, arr)  # would segfault on eager munmap
