"""End-to-end request cancellation (docs/cancellation.md): token and
registry semantics, the golden resource-release matrix (batcher queue
drop + in-flight early completion with wasted-compute billing, tenant
in-flight slot release, LLM lane reap freeing KV pages, sequence
turnstile abandonment, single-flight follower detach / leader abort),
ensemble between-stage aborts with remaining-deadline budgets, the
wire cancellation surfaces (HTTP /v2/cancel route, gRPC client-side
cancel, aio disconnect), and the chaos ``abandon_rate`` fault with
surviving-client goodput unaffected."""

import asyncio
import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from client_tpu.models.simple_extra import SequenceAccumulator
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.server import chaos
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.batcher import DynamicBatcher
from client_tpu.server.cancel import (
    REASON_CLIENT_DISCONNECT,
    CancelRegistry,
    CancelToken,
)
from client_tpu.server.http_server import start_http_server_thread
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.server.qos import TenantQuotaManager
from client_tpu.server.sequence import SequenceScheduler
from client_tpu.utils import InferenceServerException


def _wait_for(predicate, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while not predicate() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert predicate()


def _metric(core, family, labels):
    pattern = r"%s\{%s\} (\d+)" % (re.escape(family), re.escape(labels))
    match = re.search(pattern, core.metrics_text())
    return int(match.group(1)) if match else 0


# -- token + registry semantics -------------------------------------------


def test_token_cancel_idempotent_fires_callbacks_once():
    token = CancelToken()
    fired = []
    handle = token.on_cancel(lambda: fired.append("a"))
    assert token.cancel("wire_cancel") is True
    assert token.cancel("wire_cancel") is False  # idempotent
    assert fired == ["a"]
    token.remove_callback(handle)  # late remove is a no-op
    # registration after cancellation fires immediately
    token.on_cancel(lambda: fired.append("late"))
    assert fired == ["a", "late"]
    assert token.cancelled()
    assert token.reason == "wire_cancel"


def test_removed_callback_never_fires():
    token = CancelToken()
    fired = []
    handle = token.on_cancel(lambda: fired.append(1))
    token.remove_callback(handle)
    token.cancel()
    assert fired == []


def test_raise_if_cancelled_stamps_stage_and_status():
    token = CancelToken()
    token.cancel("client_disconnect")
    with pytest.raises(InferenceServerException) as exc:
        token.raise_if_cancelled("queue")
    assert exc.value.status() == "CANCELLED"
    assert exc.value.cancel_stage == "queue"
    assert token.stage == "queue"  # first raise wins the stage stamp
    with pytest.raises(InferenceServerException):
        token.raise_if_cancelled("execute")
    assert token.stage == "queue"


def test_deadline_expiry_raises_deadline_exceeded():
    now = time.monotonic_ns()
    token = CancelToken(deadline_ns=now + 50_000_000)  # 50 ms
    assert not token.expired(now)
    assert token.remaining_us(now) == 50_000
    late = now + 60_000_000
    assert token.expired(late)
    assert token.remaining_us(late) == 0  # floored, never negative
    with pytest.raises(InferenceServerException) as exc:
        token.raise_if_cancelled("ensemble", now_ns=late)
    assert exc.value.status() == "DEADLINE_EXCEEDED"
    assert exc.value.cancel_stage == "ensemble"


def test_registry_tracks_and_wire_cancels_by_id():
    registry = CancelRegistry(enabled=True)
    token = registry.mint("req-9", timeout_us=None)
    registry.track(token)
    assert registry.inflight() == 1
    assert registry.cancel("req-9") is True
    assert token.cancelled()
    assert registry.cancel("no-such-id") is False
    assert registry.unknown_id_cancels == 1
    registry.untrack(token)
    assert registry.inflight() == 0


def test_kill_switch_env(monkeypatch):
    monkeypatch.setenv("CLIENT_TPU_CANCEL", "off")
    assert not CancelRegistry().enabled
    monkeypatch.setenv("CLIENT_TPU_CANCEL", "on")
    assert CancelRegistry().enabled


# -- batcher sink ----------------------------------------------------------


class GatedModel(ServedModel):
    """Execution blocks on a per-test gate so cancels can land at a
    chosen stage; ``entered`` flips when a fused batch dispatches."""

    max_batch_size = 8
    dynamic_batching = True

    def __init__(self, name="cancel_gated"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("IN", "FP32", [4])]
        self.outputs = [TensorSpec("OUT", "FP32", [4])]
        self.gate = threading.Event()
        self.entered = threading.Event()
        self.executions = []

    def infer(self, inputs, parameters=None):
        self.entered.set()
        assert self.gate.wait(30), "test gate never released"
        array = np.asarray(inputs["IN"])
        self.executions.append([float(v) for v in array[:, 0]])
        return {"OUT": array * 2.0}


def _submit(batcher, i, cancel=None, results=None):
    def run():
        try:
            out, _, _ = batcher.infer(
                {"IN": np.full((1, 4), float(i), np.float32)}, {}, 1,
                cancel=cancel)
            results[i] = ("ok", float(out["OUT"][0, 0]))
        except InferenceServerException as e:
            results[i] = (e.status(), getattr(e, "cancel_stage", None))

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    return thread


def test_batcher_drops_queued_member_on_cancel():
    model = GatedModel()
    batcher = DynamicBatcher(model, max_queue_delay_us=1000,
                             preferred_batch_sizes=[1], pipeline_depth=1)
    results = {}
    t0 = _submit(batcher, 0, results=results)
    _wait_for(model.entered.is_set)  # request 0 dispatched, holds gate
    token = CancelToken()
    t1 = _submit(batcher, 1, cancel=token, results=results)
    _wait_for(lambda: batcher.stats_snapshot()["pending_count"] == 1)
    token.cancel(REASON_CLIENT_DISCONNECT)
    t1.join(timeout=5)  # returns while the gate is still held
    assert not t1.is_alive()
    assert results[1] == ("CANCELLED", "queue")
    assert batcher.stats_snapshot()["pending_count"] == 0  # backed out
    model.gate.set()
    t0.join(timeout=10)
    batcher.stop()
    assert results[0] == ("ok", 0.0)
    # the dropped member never executed
    assert all(1.0 not in ex for ex in model.executions)


def test_batcher_inflight_cancel_completes_early_and_bills_waste():
    model = GatedModel()
    wasted = []
    batcher = DynamicBatcher(model, max_queue_delay_us=300_000,
                             preferred_batch_sizes=[2],
                             wasted_hook=wasted.append)
    results = {}
    token = CancelToken()
    t0 = _submit(batcher, 0, results=results)
    t1 = _submit(batcher, 1, cancel=token, results=results)
    _wait_for(model.entered.is_set)  # both fused, batch in flight
    token.cancel(REASON_CLIENT_DISCONNECT)
    t1.join(timeout=5)  # early completion: never re-pads in-flight XLA
    assert not t1.is_alive()
    assert results[1] == ("CANCELLED", "execute")
    model.gate.set()
    t0.join(timeout=10)
    batcher.stop()
    assert results[0] == ("ok", 0.0)  # survivor's slice intact
    assert model.executions == [[0.0, 1.0]]  # one fused execution ran
    # the cancelled member's row-proportional compute share is billed
    assert len(wasted) == 1 and wasted[0] > 0


# -- golden resource-release matrix over the wire --------------------------


def _pb_request(model, array, name="IN", request_id="", tenant=None,
                timeout_us=None):
    request = pb.ModelInferRequest(model_name=model, id=request_id)
    tensor = request.inputs.add()
    tensor.name = name
    tensor.datatype = {"float32": "FP32", "int32": "INT32"}[
        str(array.dtype)]
    tensor.shape.extend(array.shape)
    request.raw_input_contents.append(array.tobytes())
    if tenant:
        request.parameters["tenant"].string_param = tenant
    if timeout_us:
        request.parameters["timeout"].int64_param = timeout_us
    return request


@pytest.fixture(scope="module")
def wire():
    core = build_core([], warmup=False)
    model = GatedModel()
    core.repository.add_model(model)
    core.tenant_quotas = TenantQuotaManager.from_spec(
        "default=rate:10000,burst:100,concurrency:8")
    grpc_handle = start_grpc_server(core=core)
    http_runner = start_http_server_thread(core, host="127.0.0.1",
                                           port=0)
    yield core, model, grpc_handle, http_runner
    model.gate.set()
    http_runner.stop()
    grpc_handle.stop()
    core.shutdown()


@pytest.fixture()
def fresh_gate(wire):
    _core, model, _grpc, _http = wire
    model.gate = threading.Event()
    model.entered = threading.Event()
    yield
    model.gate.set()


def test_wire_cancel_releases_tenant_slot_and_registry(wire, fresh_gate):
    core, model, _grpc, _http = wire
    before = _metric(core, "tpu_request_cancelled_total",
                     'model="cancel_gated",stage="execute"')
    outcome = {}

    def run():
        try:
            core.infer(_pb_request("cancel_gated",
                                   np.ones((1, 4), np.float32),
                                   request_id="wc-1", tenant="acme"))
            outcome["status"] = "ok"
        except InferenceServerException as e:
            outcome["status"] = e.status()
            outcome["stage"] = getattr(e, "cancel_stage", None)

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    _wait_for(model.entered.is_set)
    assert core.tenant_quotas.snapshot()["acme"]["inflight"] == 1
    assert core.cancel.inflight() == 1
    assert core.cancel_request("wc-1") is True
    thread.join(timeout=10)
    assert not thread.is_alive()
    assert outcome == {"status": "CANCELLED", "stage": "execute"}
    # golden matrix rows: tenant slot back, registry drained
    assert core.tenant_quotas.snapshot()["acme"]["inflight"] == 0
    assert core.cancel.inflight() == 0
    assert core.cancel_request("wc-1") is False  # already finished
    after = _metric(core, "tpu_request_cancelled_total",
                    'model="cancel_gated",stage="execute"')
    assert after == before + 1
    # releasing the gate lets the in-flight batch finish and bill the
    # abandoned member's compute share
    model.gate.set()
    _wait_for(lambda: _metric(core, "tpu_wasted_compute_us",
                              'model="cancel_gated"') > 0)


def test_http_cancel_route_returns_499(wire, fresh_gate):
    _core, model, _grpc, http_runner = wire
    base = "http://127.0.0.1:%d" % http_runner.port
    body = json.dumps({
        "id": "http-c1",
        "inputs": [{"name": "IN", "shape": [1, 4], "datatype": "FP32",
                    "data": [1.0, 2.0, 3.0, 4.0]}],
    }).encode()
    outcome = {}

    def run():
        request = urllib.request.Request(
            base + "/v2/models/cancel_gated/infer", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(request) as response:
                outcome["code"] = response.status
        except urllib.error.HTTPError as e:
            outcome["code"] = e.code

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    _wait_for(model.entered.is_set)
    cancel = urllib.request.Request(base + "/v2/cancel/http-c1",
                                    data=b"", method="POST")
    with urllib.request.urlopen(cancel) as response:
        assert response.status == 200
        assert json.load(response) == {"cancelled": True}
    thread.join(timeout=10)
    assert not thread.is_alive()
    assert outcome["code"] == 499  # nginx's "client closed request"
    # unknown / already-finished id: 404
    late = urllib.request.Request(base + "/v2/cancel/http-c1",
                                  data=b"", method="POST")
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(late)
    assert exc.value.code == 404
    model.gate.set()


def test_grpc_client_cancel_reaches_server_token(wire, fresh_gate):
    import grpc as grpc_mod

    from client_tpu.protocol.service import GRPCInferenceServiceStub

    core, model, grpc_handle, _http = wire
    before = _metric(core, "tpu_request_cancelled_total",
                     'model="cancel_gated",stage="execute"')
    channel = grpc_mod.insecure_channel(grpc_handle.address)
    stub = GRPCInferenceServiceStub(channel)
    future = stub.ModelInfer.future(
        _pb_request("cancel_gated", np.ones((1, 4), np.float32),
                    request_id="grpc-c1"))
    _wait_for(model.entered.is_set)
    future.cancel()  # client walks away: context callback fires
    _wait_for(lambda: _metric(
        core, "tpu_request_cancelled_total",
        'model="cancel_gated",stage="execute"') == before + 1)
    channel.close()
    model.gate.set()


def test_aio_http_disconnect_cancels_inflight_request(wire, fresh_gate):
    aiohttp = pytest.importorskip("aiohttp")
    core, model, _grpc, http_runner = wire
    before = _metric(core, "tpu_request_cancelled_total",
                     'model="cancel_gated",stage="execute"')
    url = ("http://127.0.0.1:%d/v2/models/cancel_gated/infer"
           % http_runner.port)
    payload = {
        "id": "aio-c1",
        "inputs": [{"name": "IN", "shape": [1, 4], "datatype": "FP32",
                    "data": [1.0, 1.0, 1.0, 1.0]}],
    }

    async def go():
        async with aiohttp.ClientSession() as session:
            task = asyncio.ensure_future(session.post(url, json=payload))
            loop = asyncio.get_event_loop()
            await loop.run_in_executor(None, model.entered.wait)
            task.cancel()  # closes the connection mid-request
            with pytest.raises(asyncio.CancelledError):
                await task

    asyncio.run(go())
    _wait_for(lambda: _metric(
        core, "tpu_request_cancelled_total",
        'model="cancel_gated",stage="execute"') == before + 1)
    model.gate.set()


def test_stream_cancel_ends_with_cancelled_error(wire):
    core, _model, _grpc, _http = wire
    core.repository.load("repeat_int32")
    token = CancelToken()
    request = _pb_request("repeat_int32",
                          np.array([1, 2, 3, 4], np.int32),
                          request_id="st-c1")
    before = _metric(core, "tpu_request_cancelled_total",
                     'model="repeat_int32",stage="stream"')
    stream = core.stream_infer(request, cancel=token)
    first = next(stream)
    assert not first.error_message
    token.cancel(REASON_CLIENT_DISCONNECT)
    responses = list(stream)
    assert responses, "the cancel must surface as an in-stream error"
    assert "cancelled" in responses[-1].error_message
    after = _metric(core, "tpu_request_cancelled_total",
                    'model="repeat_int32",stage="stream"')
    assert after == before + 1


# -- LLM lane reap ---------------------------------------------------------


def test_llm_cancel_token_reaps_lane_and_frees_pages():
    from client_tpu.models.llm import LlmConfig, LlmModel

    model = LlmModel(
        name="llm_cancel_token",
        cfg=LlmConfig(d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                      d_ff=128, max_seq=128),
        paged_kv=True, decode_lanes=2, page_size=4)
    try:
        token = CancelToken()
        gen = model._generate(
            {"text_input": np.array([b"abandoned stream"],
                                    dtype=np.object_),
             "max_tokens": np.array([200], dtype=np.int32),
             "ignore_eos": np.array([True])},
            {"cancel_token": token})
        next(gen)
        assert model.kv_stats()["pages_used"] > 0
        token.cancel(REASON_CLIENT_DISCONNECT)
        list(gen)  # the reap posts the end sentinel; no 200-token wait
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = model.kv_stats()
            if not (snap["pages_used"] or snap["pages_reserved"]):
                break
            time.sleep(0.05)
        snap = model.kv_stats()
        assert snap["pages_used"] == 0 and snap["pages_reserved"] == 0
        # the lane is immediately reusable by a surviving client
        survivor = list(model._generate(
            {"text_input": np.array([b"next"], dtype=np.object_),
             "max_tokens": np.array([4], dtype=np.int32),
             "ignore_eos": np.array([True])}, {}))
        assert len(survivor) == 4
    finally:
        model.unload()


# -- sequence turnstile ----------------------------------------------------


def test_sequence_cancelled_waiter_abandons_ticket_without_wedging():
    class SlowSeq(SequenceAccumulator):
        def infer(self, inputs, parameters=None):
            time.sleep(0.2)
            return super().infer(inputs, parameters)

    model = SlowSeq(name="cancel_seq")
    scheduler = SequenceScheduler(model)
    results = {}

    def step(key, value, start=False, end=False, cancel=None):
        try:
            out, _, _ = scheduler.infer(
                {"INPUT": np.array([value], dtype=np.int32)},
                {"sequence_id": 77, "sequence_start": start,
                 "sequence_end": end}, 1, cancel=cancel)
            results[key] = ("ok",
                            int(np.asarray(out["OUTPUT"]).reshape(-1)[0]))
        except InferenceServerException as e:
            results[key] = (e.status(), getattr(e, "cancel_stage", None))

    token = CancelToken()
    threads = [threading.Thread(target=step, args=("s1", 1, True))]
    threads[0].start()
    time.sleep(0.05)  # s1 admitted, executing: holds the turn
    threads.append(threading.Thread(
        target=step, args=("s2", 2), kwargs={"cancel": token}))
    threads[1].start()
    time.sleep(0.05)  # s2 ticketed behind s1
    threads.append(threading.Thread(
        target=step, args=("s3", 3), kwargs={"end": True}))
    threads[2].start()
    time.sleep(0.05)
    token.cancel(REASON_CLIENT_DISCONNECT)
    for thread in threads:
        thread.join(timeout=10)
        assert not thread.is_alive()
    assert results["s1"] == ("ok", 1)
    assert results["s2"] == ("CANCELLED", "queue")
    # the turnstile skipped the abandoned ticket: s3 still served
    assert results["s3"] == ("ok", 4)  # 1 + 3; the cancelled 2 never ran
    snap = scheduler.stats_snapshot()
    assert snap["active_sequences"] == 0  # slot reclaimed at end
    scheduler.stop()


# -- single-flight (response cache) ----------------------------------------


class SlowCached(ServedModel):
    response_cache = True
    max_batch_size = 0

    def __init__(self, name="cancel_sf", delay_s=0.5):
        super().__init__()
        self.name = name
        self.delay_s = delay_s
        self.inputs = [TensorSpec("IN", "FP32", [4])]
        self.outputs = [TensorSpec("OUT", "FP32", [4])]
        self.entered = threading.Event()
        self.calls = 0

    def infer(self, inputs, parameters=None):
        self.calls += 1
        self.entered.set()
        time.sleep(self.delay_s)
        return {"OUT": np.asarray(inputs["IN"]) * 3.0}


def _sf_infer(core, model_name, value, outcome, key, cancel=None):
    def run():
        try:
            response = core.infer(
                _pb_request(model_name,
                            np.full((4,), float(value), np.float32)),
                cancel=cancel)
            out = np.frombuffer(response.raw_output_contents[0],
                                np.float32)
            outcome[key] = ("ok", float(out[0]))
        except InferenceServerException as e:
            outcome[key] = (e.status(), getattr(e, "cancel_stage", None))

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    return thread


def test_cancelled_follower_detaches_without_killing_leader():
    core = build_core([], warmup=False)
    model = SlowCached("cancel_sf", delay_s=0.6)
    core.repository.add_model(model)
    outcome = {}
    leader = _sf_infer(core, "cancel_sf", 5, outcome, "leader")
    _wait_for(model.entered.is_set)
    token = CancelToken()
    follower = _sf_infer(core, "cancel_sf", 5, outcome, "follower",
                         cancel=token)
    time.sleep(0.15)  # follower parked on the leader's flight
    token.cancel(REASON_CLIENT_DISCONNECT)
    follower.join(timeout=5)
    assert not follower.is_alive()
    assert outcome["follower"] == ("CANCELLED", "queue")
    leader.join(timeout=10)
    assert outcome["leader"] == ("ok", 15.0)  # leader unharmed
    assert model.calls == 1
    # burst resolved: an identical request now hits the cache
    third = _sf_infer(core, "cancel_sf", 5, outcome, "third")
    third.join(timeout=5)
    assert outcome["third"] == ("ok", 15.0)
    assert model.calls == 1  # cache hit, no re-execution
    core.shutdown()


def test_cancelled_leader_aborts_surviving_follower_reexecutes():
    core = build_core([], warmup=False)
    model = SlowCached("cancel_sf2", delay_s=0.4)
    core.repository.add_model(model)
    outcome = {}
    token = CancelToken()
    leader = _sf_infer(core, "cancel_sf2", 7, outcome, "leader",
                       cancel=token)
    _wait_for(model.entered.is_set)
    follower = _sf_infer(core, "cancel_sf2", 7, outcome, "follower")
    time.sleep(0.1)
    token.cancel(REASON_CLIENT_DISCONNECT)
    leader.join(timeout=10)
    assert outcome["leader"][0] == "CANCELLED"
    # the non-cancelled follower falls back to its own execution
    follower.join(timeout=10)
    assert not follower.is_alive()
    assert outcome["follower"] == ("ok", 21.0)
    core.shutdown()


# -- ensembles -------------------------------------------------------------


class _RecStage(ServedModel):
    """Direct composing stage recording the timeout budget it was
    handed; optionally cancels a token mid-stage (the disconnect that
    lands while stage k runs)."""

    max_batch_size = 8

    def __init__(self, name, in_name, out_name, scale, sleep_s=0.0):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec(in_name, "FP32", [4])]
        self.outputs = [TensorSpec(out_name, "FP32", [4])]
        self._in, self._out, self._scale = in_name, out_name, scale
        self._sleep_s = sleep_s
        self.seen_timeouts = []
        self.cancel_during = None
        self.calls = 0

    def infer(self, inputs, parameters=None):
        self.calls += 1
        self.seen_timeouts.append((parameters or {}).get("timeout"))
        if self._sleep_s:
            time.sleep(self._sleep_s)
        if self.cancel_during is not None:
            self.cancel_during.cancel(REASON_CLIENT_DISCONNECT)
        x = np.asarray(inputs[self._in], dtype=np.float32)
        return {self._out: x * np.float32(self._scale)}


@pytest.fixture()
def ensemble_core():
    from client_tpu.models.ensemble import EnsembleModel

    core = build_core([], warmup=False)
    repo = core.repository
    edge = _RecStage("c_edge", "XIN", "H", 2.0, sleep_s=0.05)
    tail = _RecStage("c_tail", "H", "OUT", 3.0)
    repo.add_model(edge)
    repo.add_model(tail)
    repo.add_factory("c_ens", lambda: EnsembleModel(
        name="c_ens", repository=repo,
        steps=[("c_edge", {"XIN": "XIN"}, {"h": "H"}),
               ("c_tail", {"h": "H"}, {"OUT": "OUT"})],
        inputs=[TensorSpec("XIN", "FP32", [4])],
        outputs=[TensorSpec("OUT", "FP32", [4])],
        max_batch_size=8))
    core.load_model("c_ens", warmup=False)
    yield core, edge, tail
    core.shutdown()


def test_ensemble_cancel_between_stages_aborts_subgraph(ensemble_core):
    core, edge, tail = ensemble_core
    token = CancelToken()
    edge.cancel_during = token  # disconnect lands while stage 1 runs
    with pytest.raises(InferenceServerException) as exc:
        core.infer(_pb_request("c_ens", np.ones((1, 4), np.float32),
                               name="XIN"), cancel=token)
    assert exc.value.status() == "CANCELLED"
    assert exc.value.cancel_stage == "ensemble"
    assert edge.calls == 1
    assert tail.calls == 0  # the remaining subgraph never ran
    assert _metric(core, "tpu_request_cancelled_total",
                   'model="c_ens",stage="ensemble"') == 1


def test_ensemble_stages_get_remaining_deadline_budget(ensemble_core):
    core, edge, tail = ensemble_core
    response = core.infer(
        _pb_request("c_ens", np.ones((1, 4), np.float32), name="XIN",
                    timeout_us=2_000_000))
    out = np.frombuffer(response.raw_output_contents[0], np.float32)
    np.testing.assert_allclose(out, np.full(4, 6.0), rtol=1e-6)
    edge_budget = edge.seen_timeouts[-1]
    tail_budget = tail.seen_timeouts[-1]
    assert edge_budget is not None and tail_budget is not None
    assert int(edge_budget) <= 2_000_000
    # stage 1 slept 50 ms: stage 2's budget shrank by the elapsed time
    assert int(tail_budget) <= int(edge_budget) - 30_000


# -- chaos abandon_rate ----------------------------------------------------


class QuickModel(ServedModel):
    max_batch_size = 0

    def __init__(self, name="abandon_quick"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("IN", "FP32", [4])]
        self.outputs = [TensorSpec("OUT", "FP32", [4])]

    def infer(self, inputs, parameters=None):
        time.sleep(0.01)
        return {"OUT": np.asarray(inputs["IN"]) + 1.0}


def test_chaos_abandon_cancels_sampled_requests_survivors_unaffected():
    core = build_core([], warmup=False)
    core.repository.add_model(QuickModel())
    chaos.configure(chaos.ChaosConfig(abandon_rate=0.5, seed=11))
    cancelled, ok = 0, 0
    try:
        before = chaos.stats()["abandoned_requests"]
        for i in range(20):
            token = core.cancel.mint("ab-%d" % i)
            try:
                response = core.infer(
                    _pb_request("abandon_quick",
                                np.full((4,), float(i), np.float32),
                                request_id="ab-%d" % i),
                    cancel=token)
                out = np.frombuffer(response.raw_output_contents[0],
                                    np.float32)
                # surviving-client goodput: correct answers, not junk
                np.testing.assert_allclose(out, np.full(4, i + 1.0))
                ok += 1
            except InferenceServerException as e:
                assert e.status() == "CANCELLED"
                cancelled += 1
        abandoned = chaos.stats()["abandoned_requests"] - before
    finally:
        chaos.configure(None)
        core.shutdown()
    assert cancelled > 0 and ok > 0  # the coin actually flipped
    assert cancelled == abandoned
    assert cancelled + ok == 20


def test_chaos_abandon_inert_without_token():
    core = build_core([], warmup=False)
    core.repository.add_model(QuickModel(name="abandon_inert"))
    core.cancel.enabled = False  # kill switch: no token minted
    chaos.configure(chaos.ChaosConfig(abandon_rate=1.0, seed=5))
    try:
        before = chaos.stats()["abandoned_requests"]
        response = core.infer(_pb_request(
            "abandon_inert", np.ones((4,), np.float32)))
        assert response.raw_output_contents  # served normally
        assert chaos.stats()["abandoned_requests"] == before
    finally:
        chaos.configure(None)
        core.shutdown()
