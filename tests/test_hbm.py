"""HBM-allocator tests (client_tpu.server.hbm).

Covers the PR-18 tentpole: budget parsing and admission against a
simulated budget, ledger-driven eviction (coldest-first by idle age,
never the requesting model), the arbitration queue under two
concurrent scale-ups racing one budget (exactly one honest retryable
deferral, never an OOM), weight paging round trips (bit-identical
host copies, golden inference parity through a live core, the
admission-miss background restore), ledger residual ~0 after
page-out/restore churn, and the autoscaler's scale-to-zero riding
the page-out path for pageable models (snapshot ``cold_mode``)."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from client_tpu._infer_common import InferInput
from client_tpu.grpc._utils import get_inference_request
from client_tpu.models.add_sub import AddSub
from client_tpu.server import devstats as devstats_mod
from client_tpu.server import hbm as hbm_mod
from client_tpu.server.app import build_core
from client_tpu.utils import InferenceServerException


def _request(value, model, shape=(16,), **kwargs):
    tensors = []
    for name, fill in (("INPUT0", value), ("INPUT1", 2 * value)):
        tensor = InferInput(name, list(shape), "INT32")
        tensor.set_data_from_numpy(np.full(shape, fill, dtype=np.int32))
        tensors.append(tensor)
    return get_inference_request(model_name=model, inputs=tensors,
                                 outputs=None, **kwargs)


def _wait_for(predicate, timeout_s=8.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


def _allocator(budget):
    return hbm_mod.HbmAllocator(
        budget_bytes=budget, stats=devstats_mod.DeviceStats(enabled=True))


class _FakePager:
    """Order-recording stand-in for WeightPager in pure-allocator
    tests (no device arrays involved)."""

    def __init__(self, name, order=None, fail=False):
        self.name = name
        self.order = order if order is not None else []
        self.fail = fail
        self.paged = 0
        self.restored = 0

    def page_out(self):
        if self.fail:
            raise RuntimeError("injected page-out failure")
        self.paged += 1
        self.order.append(self.name)
        return {"host": self.name}

    def restore(self, host_state):
        self.restored += 1


class _BiasAddSub(AddSub):
    """AddSub plus a learned bias — the smallest model with real
    pageable weights: OUTPUT0 = a + b + bias, OUTPUT1 = a - b + bias."""

    def __init__(self, name, bias=3):
        super().__init__(name=name, datatype="INT32", shape=(16,))
        self.pageable_weights = True
        self._bias = jnp.full((16,), bias, dtype=jnp.int32)

    def infer(self, inputs, parameters=None):
        a = np.asarray(inputs["INPUT0"])
        b = np.asarray(inputs["INPUT1"])
        bias = np.asarray(self._bias)
        return {"OUTPUT0": a + b + bias, "OUTPUT1": a - b + bias}

    def weight_state(self):
        return {"bias": self._bias}

    def set_weight_state(self, state):
        self._bias = state["bias"]


def _bias_factory(name, **autoscale):
    def factory():
        model = _BiasAddSub(name)
        model.max_batch_size = 0
        for attr, value in autoscale.items():
            setattr(model, attr, value)
        return model
    return factory


# -- budget parsing ---------------------------------------------------------


def test_parse_budget_suffixes_and_garbage():
    assert hbm_mod._parse_budget("512m") == 512 << 20
    assert hbm_mod._parse_budget("2g") == 2 << 30
    assert hbm_mod._parse_budget("64K") == 64 << 10
    assert hbm_mod._parse_budget("1000") == 1000
    assert hbm_mod._parse_budget("1.5k") == 1536
    assert hbm_mod._parse_budget("") is None
    assert hbm_mod._parse_budget(None) is None
    assert hbm_mod._parse_budget("garbage") is None
    assert hbm_mod._parse_budget("0") is None


# -- admission --------------------------------------------------------------


def test_admission_deferral_and_release():
    alloc = _allocator(1000)
    first = alloc.lease("a", "weights", 400)
    second = alloc.lease("b", "weights", 400)
    # Nothing pageable is resident: the third scale-up loses honestly.
    with pytest.raises(InferenceServerException) as raised:
        alloc.lease("c", "weights", 400)
    assert raised.value.status() == "RESOURCE_EXHAUSTED"
    assert raised.value.retry_after_s >= hbm_mod.MIN_RESTORE_ESTIMATE_S
    snap = alloc.debug_snapshot()
    assert snap["deferrals"] == 1
    (dev,) = snap["devices"].values()
    assert dev["free_bytes"] == 200
    alloc.release(first)
    alloc.release(second)
    alloc.release(second)  # idempotent
    (dev,) = alloc.debug_snapshot()["devices"].values()
    assert dev["free_bytes"] == 1000
    # No attribution residue either.
    assert alloc._stats.ledger.model_bytes("a") == {}
    assert alloc._stats.ledger.model_bytes("b") == {}


def test_oversize_request_raises_immediately_nonretryable():
    alloc = _allocator(1000)
    # Bigger than the whole device is a PERMANENT condition, not
    # pressure: the error must not carry a Retry-After, or clients
    # would retry it forever.
    with pytest.raises(InferenceServerException) as raised:
        alloc.lease("huge", "weights", 2000)
    assert raised.value.status() == "INVALID_ARGUMENT"
    assert getattr(raised.value, "retry_after_s", None) is None


def test_zero_and_best_effort_leases():
    alloc = _allocator(100)
    assert alloc.lease("m", "weights", 0) is None
    # Best-effort overcommit never raises; free clamps at zero.
    lease = alloc.lease("m", "ensemble_interior", 500, best_effort=True)
    assert lease is not None
    (dev,) = alloc.debug_snapshot()["devices"].values()
    assert dev["free_bytes"] == 0
    assert dev["leased_bytes"] == 500
    alloc.release(lease)


# -- eviction ---------------------------------------------------------------


def test_eviction_coldest_first_by_idle_age():
    alloc = _allocator(1000)
    order = []
    leases = {}
    for name in ("a", "b", "c"):
        leases[name] = alloc.lease(
            name, "weights", 300, pageable=True,
            pager=_FakePager(name, order))
    now = time.monotonic()
    leases["b"].last_used = now - 100.0  # coldest
    leases["a"].last_used = now - 50.0
    leases["c"].last_used = now          # hot
    # 650 needs two evictions: b first (coldest), then a; c is hot
    # enough to survive.
    alloc.lease("d", "weights", 650)
    assert order == ["b", "a"]
    assert leases["b"].state == hbm_mod.PAGED_OUT
    assert leases["a"].state == hbm_mod.PAGED_OUT
    assert leases["c"].state == hbm_mod.RESIDENT
    snap = alloc.debug_snapshot()
    assert {"model": "b", "component": "weights",
            "reason": "admission", "count": 1} in snap["evictions"]
    assert snap["paged_out"] == ["a", "b"]
    # The paged rows stay attributable in the ledger's side table.
    assert alloc._stats.ledger.paged_snapshot() == {
        "a": {"weights": 300}, "b": {"weights": 300}}


def test_eviction_never_touches_requesting_model():
    alloc = _allocator(1000)
    own = alloc.lease("solo", "weights", 600, pageable=True,
                      pager=_FakePager("solo"))
    with pytest.raises(InferenceServerException):
        alloc.lease("solo", "kv_pages", 600)
    assert own.state == hbm_mod.RESIDENT
    assert own.pager.paged == 0


def test_failed_pageout_victim_is_skipped_and_unquiesced():
    alloc = _allocator(1000)
    victim = alloc.lease("sick", "weights", 600, pageable=True,
                         pager=_FakePager("sick", fail=True))
    calls = {"quiesce": 0, "ready": 0}
    victim.on_page_out = lambda: calls.__setitem__(
        "quiesce", calls["quiesce"] + 1)
    victim.on_restore = lambda: calls.__setitem__(
        "ready", calls["ready"] + 1)
    with pytest.raises(InferenceServerException):
        alloc.lease("next", "weights", 600)
    # The victim stayed resident and its quiesce was undone — a
    # failed copy must not strand a model UNAVAILABLE.
    assert victim.state == hbm_mod.RESIDENT
    assert calls == {"quiesce": 1, "ready": 1}


# -- release racing an in-flight transfer -----------------------------------


class _GatedPager:
    """Pager whose transfers park on an event — lets a test land a
    release() in the middle of a page-out or restore copy."""

    def __init__(self, block_page_out=False, block_restore=False):
        self.started = threading.Event()
        self.proceed = threading.Event()
        self._block_page_out = block_page_out
        self._block_restore = block_restore

    def _gate(self, blocked):
        if blocked:
            self.started.set()
            assert self.proceed.wait(8.0), "test gate never opened"

    def page_out(self):
        self._gate(self._block_page_out)
        return {"host": 1}

    def restore(self, host_state):
        self._gate(self._block_restore)


def test_release_during_page_out_stays_terminal():
    """An unload landing mid-page-out must not resurrect the lease or
    settle its device bytes twice (the keeper lease would be the one
    silently over-admitted against)."""
    alloc = _allocator(1000)
    keeper = alloc.lease("keep", "weights", 300)
    pager = _GatedPager(block_page_out=True)
    doomed = alloc.lease("m", "weights", 400, pageable=True, pager=pager)
    worker = threading.Thread(target=alloc.page_out, args=(doomed,))
    worker.start()
    assert pager.started.wait(8.0)
    alloc.release(doomed)  # unload racing the device->host copy
    pager.proceed.set()
    worker.join(8.0)
    assert not worker.is_alive()
    assert doomed.state == hbm_mod.RELEASED
    assert doomed.host_state is None
    (dev,) = alloc.debug_snapshot()["devices"].values()
    assert dev["leased_bytes"] == 300  # keeper intact, no double-free
    assert alloc._stats.ledger.model_bytes("m") == {}
    assert alloc._stats.ledger.paged_snapshot() == {}
    alloc.release(keeper)


def test_release_during_restore_stays_terminal():
    """An unload landing mid-restore must not flip the lease back to
    RESIDENT (mark_ready on a mid-teardown model) and must hand the
    admission reserve back."""
    alloc = _allocator(1000)
    keeper = alloc.lease("keep", "weights", 300)
    pager = _GatedPager(block_restore=True)
    doomed = alloc.lease("m", "weights", 400, pageable=True, pager=pager)
    readies = {"count": 0}
    doomed.on_restore = lambda: readies.__setitem__(
        "count", readies["count"] + 1)
    assert alloc.page_out(doomed) == 400
    results = {}

    def run():
        results["restored"] = alloc.restore(doomed)

    worker = threading.Thread(target=run)
    worker.start()
    assert pager.started.wait(8.0)
    alloc.release(doomed)  # unload racing the host->device upload
    pager.proceed.set()
    worker.join(8.0)
    assert not worker.is_alive()
    assert results["restored"] is False
    assert doomed.state == hbm_mod.RELEASED
    assert readies["count"] == 0  # never marked ready mid-teardown
    (dev,) = alloc.debug_snapshot()["devices"].values()
    assert dev["leased_bytes"] == 300  # reserve given back
    assert alloc._stats.ledger.model_bytes("m") == {}
    assert alloc._stats.ledger.paged_snapshot() == {}
    alloc.release(keeper)


# -- arbitration ------------------------------------------------------------


def test_two_concurrent_scaleups_one_budget():
    alloc = _allocator(1000)
    barrier = threading.Barrier(2)
    results = {}

    def scale_up(name):
        barrier.wait()
        try:
            results[name] = alloc.lease(name, "weights", 600)
        except InferenceServerException as e:
            results[name] = e

    threads = [threading.Thread(target=scale_up, args=(name,))
               for name in ("x", "y")]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    winners = [r for r in results.values()
               if isinstance(r, hbm_mod.HbmLease)]
    losers = [r for r in results.values()
              if isinstance(r, InferenceServerException)]
    # Serialized admission: exactly one wins, the loser gets the
    # honest retryable deferral — never both admitted, never an OOM.
    assert len(winners) == 1 and len(losers) == 1
    assert losers[0].status() == "RESOURCE_EXHAUSTED"
    assert losers[0].retry_after_s > 0
    assert alloc.debug_snapshot()["deferrals"] == 1


# -- paging round trips -----------------------------------------------------


def test_weight_pager_round_trip_bit_identical():
    model = _BiasAddSub("pager_parity", bias=7)
    golden = np.asarray(model._bias).copy()
    pager = hbm_mod.WeightPager(model)
    host_state = pager.page_out()
    assert isinstance(model._bias, np.ndarray)  # host copies installed
    assert np.array_equal(np.asarray(model._bias), golden)
    pager.restore(host_state)
    assert not isinstance(model._bias, np.ndarray)  # device again
    assert np.array_equal(np.asarray(model._bias), golden)


def test_allocator_restore_measures_bandwidth_and_ledger():
    alloc = _allocator(None)  # accounting-only: page/restore still work
    lease = alloc.lease("m", "weights", 4096, pageable=True,
                        pager=_FakePager("m"))
    assert alloc.page_out(lease) == 4096
    assert lease.state == hbm_mod.PAGED_OUT
    assert alloc.paged_out_models() == ["m"]
    assert alloc._stats.ledger.model_bytes("m") == {}
    assert alloc._stats.ledger.paged_snapshot() == {
        "m": {"weights": 4096}}
    assert alloc.restore(lease)
    assert lease.state == hbm_mod.RESIDENT
    assert lease.pager.restored == 1
    assert alloc.paged_out_models() == []
    assert alloc._stats.ledger.paged_snapshot() == {}
    assert alloc._stats.ledger.model_bytes("m") == {"weights": 4096}
    # One measured restore replaced the bandwidth prior and landed in
    # the exposition families.
    assert alloc.restore_bandwidth() != hbm_mod.DEFAULT_RESTORE_BANDWIDTH
    text = "\n".join(alloc.render_metrics())
    assert 'tpu_weight_pageout_total{model="m"} 1' in text
    assert "tpu_weight_restore_us" in text
    alloc.release(lease)
    assert alloc._stats.ledger.model_bytes("m") == {}


def test_ledger_residual_zero_after_churn():
    alloc = _allocator(8192)
    lease = alloc.lease("churn", "weights", 2048, pageable=True,
                        pager=_FakePager("churn"))
    for _ in range(5):
        assert alloc.page_out(lease) == 2048
        assert alloc.restore(lease)
    (dev,) = alloc.debug_snapshot()["devices"].values()
    assert dev["leased_bytes"] == 2048
    assert alloc._stats.ledger.model_bytes("churn") == {"weights": 2048}
    assert alloc._stats.ledger.paged_snapshot() == {}
    alloc.release_model("churn")
    (dev,) = alloc.debug_snapshot()["devices"].values()
    assert dev["leased_bytes"] == 0
    assert alloc._stats.ledger.model_bytes("churn") == {}


# -- through a live core ----------------------------------------------------


def test_core_page_out_restore_golden_parity():
    core = build_core([], warmup=False)
    name = "hbm_parity"
    try:
        core.repository.add_factory(name, _bias_factory(name))
        core.load_model(name, warmup=False)
        golden = core.infer(_request(5, name))
        info = core.page_out_model(name)
        assert info is not None and info["nbytes"] > 0
        assert info["restore_estimate_s"] >= hbm_mod.MIN_RESTORE_ESTIMATE_S
        assert not core.repository.is_ready(name)
        # The debug document names the paged-out set.
        assert name in core.debug_snapshot()["hbm"]["paged_out"]
        # First arrival: honest 503 + Retry-After, and it kicks the
        # single-flight background restore.
        with pytest.raises(InferenceServerException) as raised:
            core.infer(_request(5, name))
        assert raised.value.status() == "UNAVAILABLE"
        assert raised.value.retry_after_s > 0
        assert "cold-starting" in str(raised.value)
        assert _wait_for(lambda: core.repository.is_ready(name))
        after = core.infer(_request(5, name))
        assert list(after.raw_output_contents) == \
            list(golden.raw_output_contents)
        assert "tpu_weight_pageout_total" in core.metrics_text()
    finally:
        try:
            core.unload_model(name)
        finally:
            core.shutdown()


def test_core_unload_sweeps_hbm_leases():
    core = build_core([], warmup=False)
    name = "hbm_sweep"
    try:
        core.repository.add_factory(name, _bias_factory(name))
        core.load_model(name, warmup=False)
        assert core.hbm.weight_lease(name) is not None
        assert core.page_out_model(name) is not None  # paged residue too
        core.unload_model(name)
        assert core.hbm.weight_lease(name) is None
        assert core.devstats.ledger.model_bytes(name) == {}
        assert core.devstats.ledger.paged_snapshot().get(name) is None
    finally:
        core.shutdown()


def test_explicit_load_of_paged_model_restores():
    core = build_core([], warmup=False)
    name = "hbm_reload"
    try:
        core.repository.add_factory(name, _bias_factory(name))
        core.load_model(name, warmup=False)
        assert core.page_out_model(name) is not None
        # An explicit load of a paged model restores in place instead
        # of double-loading (no second weights lease).
        core.load_model(name, warmup=False)
        lease = core.hbm.weight_lease(name)
        assert lease is not None and lease.state == hbm_mod.RESIDENT
        assert len(core.hbm._by_model.get(name, ())) == 1
        core.infer(_request(1, name))
    finally:
        try:
            core.unload_model(name)
        finally:
            core.shutdown()


# -- scale-to-zero rides page-out -------------------------------------------


def test_scale_to_zero_pages_out_pageable_model():
    core = build_core([], warmup=False)
    name = "hbm_zero"
    try:
        core.repository.add_factory(name, _bias_factory(
            name,
            autoscale_min_replicas=0,
            autoscale_max_replicas=2,
            autoscale_idle_s=0.2,
            autoscale_interval_s=0.05,
            autoscale_up_cooldown_s=0.0,
            autoscale_down_cooldown_s=0.0))
        core.load_model(name, warmup=False)
        core.autoscaler.stop()  # hand-driven ticks
        golden = core.infer(_request(4, name))

        drained = _wait_for(
            lambda: core.autoscaler.tick_once() is not None
            and not core.repository.is_ready(name))
        assert drained, "idle model never scaled to zero"
        # Cheap cold: weights on host, ledger rows parked (not gone),
        # the controller remembers WHICH path it took.
        snapshot = core.autoscaler.snapshot()[name]
        assert snapshot["cold"]
        assert snapshot["cold_mode"] == "paged"
        assert core.devstats.ledger.model_bytes(name) == {}
        assert name in core.devstats.ledger.paged_snapshot()

        with pytest.raises(InferenceServerException) as raised:
            core.infer(_request(4, name))
        assert raised.value.status() == "UNAVAILABLE"
        assert raised.value.retry_after_s > 0
        assert _wait_for(lambda: core.repository.is_ready(name))
        after = core.infer(_request(4, name))
        assert list(after.raw_output_contents) == \
            list(golden.raw_output_contents)
        events = core.autoscaler.snapshot()[name]["events"]
        assert events.get("down|scale_to_zero") == 1
    finally:
        try:
            core.unload_model(name)
        finally:
            core.shutdown()
