"""The embedding surface (client_tpu.server.embed) that backs the
native perf harness's in_process service kind: serialized-proto
inference plus JSON metadata/statistics, no RPC."""

import json

import numpy as np
import pytest

from client_tpu.protocol import inference_pb2 as pb
from client_tpu.server import embed


@pytest.fixture(scope="module")
def embedded():
    embed.init("simple")
    yield embed
    embed.shutdown()


def _simple_request():
    # Explicit id: the server mints a fresh one per request when the
    # client sends none (request-id correlation), so byte-for-byte
    # comparisons across calls need a pinned id.
    request = pb.ModelInferRequest(model_name="simple", id="embed-req")
    for name in ("INPUT0", "INPUT1"):
        tensor = request.inputs.add()
        tensor.name = name
        tensor.datatype = "INT32"
        tensor.shape.extend([16])
        request.raw_input_contents.append(
            np.arange(16, dtype=np.int32).tobytes())
    return request


def test_infer_bytes_round_trip(embedded):
    response = pb.ModelInferResponse()
    response.ParseFromString(
        embedded.infer(_simple_request().SerializeToString()))
    out0 = np.frombuffer(response.raw_output_contents[0], np.int32)
    np.testing.assert_array_equal(out0, np.arange(16) * 2)


def test_infer_unknown_model_raises_with_status(embedded):
    request = pb.ModelInferRequest(model_name="no_such_model")
    with pytest.raises(Exception, match=r"\[NOT_FOUND\]"):
        embedded.infer(request.SerializeToString())


def test_metadata_and_config_json(embedded):
    meta = json.loads(embedded.model_metadata_json("simple"))
    assert meta["name"] == "simple"
    assert {t["name"] for t in meta["inputs"]} == {"INPUT0", "INPUT1"}
    # snake_case keys — the native ModelParser reads these directly
    # (proto3 JSON omits zero-default fields, so use a batching model).
    embedded.load_model("preprocess")
    config = json.loads(embedded.model_config_json("preprocess"))
    assert config.get("max_batch_size") == 32


def test_statistics_json_counts_are_numbers(embedded):
    embedded.infer(_simple_request().SerializeToString())
    stats = json.loads(embedded.model_statistics_json("simple"))
    entry = stats["model_stats"][0]
    assert isinstance(entry["inference_count"], int)  # not proto strings
    assert entry["inference_count"] >= 1
    assert entry["inference_stats"]["success"]["count"] >= 1


_STREAM_PATH = "/inference.GRPCInferenceService/ModelStreamInfer"


def test_stream_call_emit_delivers_incrementally(embedded):
    got = []
    embedded.grpc_stream_call_emit(
        _STREAM_PATH, _simple_request().SerializeToString(), got.append)
    assert len(got) == 1
    response = pb.ModelStreamInferResponse()
    response.ParseFromString(got[0])
    out0 = np.frombuffer(
        response.infer_response.raw_output_contents[0], np.int32)
    np.testing.assert_array_equal(out0, np.arange(16) * 2)


def test_stream_call_emit_stops_when_emit_reports_peer_gone(embedded):
    calls = []

    def emit(payload):
        calls.append(payload)
        return False  # peer disconnected after the first message

    embedded.grpc_stream_call_emit(
        _STREAM_PATH, _simple_request().SerializeToString(), emit)
    assert len(calls) == 1  # producer stopped, no error raised


def test_stream_call_list_variant_matches_emit(embedded):
    listed = embedded.grpc_stream_call(
        _STREAM_PATH, _simple_request().SerializeToString())
    emitted = []
    embedded.grpc_stream_call_emit(
        _STREAM_PATH, _simple_request().SerializeToString(),
        lambda payload: emitted.append(payload) or True)
    assert listed == emitted


def test_arena_allocate_and_register(embedded):
    handle = embedded.tpu_arena_allocate(1024)
    assert isinstance(handle, bytes) and handle
    embedded.register_tpu_shared_memory("embed_r0", handle, 0, 1024)
    embedded.unregister_tpu_shared_memory("embed_r0")


def test_arena_pull_region_streams_through_embed(embedded):
    """The DCN pull RPC is reachable through the native front-end's
    dispatch registry: PullRegion is a server-streaming method with a
    unary request, adapted onto the embed stream path."""
    from client_tpu.protocol import arena_pb2

    handle = embedded.tpu_arena_allocate(256)
    path = "/inference.TpuArenaService/PullRegion"
    assert embedded.grpc_method_kind(path) == "stream"
    write = arena_pb2.WriteRegionRequest(
        region_id=json.loads(handle)["region_id"],
        offset=0, data=np.arange(16, dtype=np.int32).tobytes(),
        datatype="INT32", shape=[16])
    embedded.grpc_call("/inference.TpuArenaService/WriteRegion",
                       write.SerializeToString())
    request = arena_pb2.PullRegionRequest(raw_handle=handle,
                                          chunk_bytes=16)
    chunks = [arena_pb2.PullRegionChunk.FromString(raw)
              for raw in embedded.grpc_stream_call(
                  path, request.SerializeToString())]
    assert chunks[0].region_byte_size == 256
    assert chunks[0].datatype == "INT32"
    assert len(chunks) == 4  # 64 bytes in 16-byte chunks
    payload = b"".join(c.data for c in chunks)
    np.testing.assert_array_equal(
        np.frombuffer(payload, np.int32), np.arange(16, dtype=np.int32))
