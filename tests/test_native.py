"""Builds the native (C++) layer and runs its unit-test binaries.

Mirrors the reference's tier-1 strategy (SURVEY.md §4: doctest unit
binaries run by CTest) — here each native test binary is exposed as
one pytest case so `python -m pytest tests/` covers the C++ layer too.
"""

import pathlib
import shutil
import subprocess

import pytest

pytestmark = pytest.mark.slow  # native cmake build + live-server e2e

REPO = pathlib.Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"
BUILD = NATIVE / "build"


def _build_native():
    if shutil.which("cmake") is None or shutil.which("ninja") is None:
        pytest.skip("cmake/ninja not available")
    if not (BUILD / "build.ninja").exists():
        subprocess.run(
            ["cmake", "-S", str(NATIVE), "-B", str(BUILD), "-G", "Ninja"],
            check=True, capture_output=True,
        )
    proc = subprocess.run(
        ["ninja", "-C", str(BUILD)], capture_output=True, text=True
    )
    if proc.returncode != 0:
        raise AssertionError(
            "native build failed:\n%s\n%s" % (proc.stdout[-4000:],
                                              proc.stderr[-4000:])
        )


@pytest.fixture(scope="session")
def native_build():
    _build_native()
    return BUILD


def _run_binary(build_dir: pathlib.Path, name: str, env_extra=None):
    import os

    binary = build_dir / name
    assert binary.exists(), "%s not built" % name
    env = dict(os.environ, **env_extra) if env_extra else None
    proc = subprocess.run(
        [str(binary)], capture_output=True, text=True, timeout=300, env=env
    )
    assert proc.returncode == 0, "%s failed:\n%s\n%s" % (
        name, proc.stdout[-4000:], proc.stderr[-4000:]
    )


def test_native_core(native_build):
    _run_binary(native_build, "test_core")


def test_native_http_offline(native_build):
    _run_binary(native_build, "test_http_client")


def test_native_hpack(native_build):
    _run_binary(native_build, "test_hpack")


def test_native_grpc_offline(native_build):
    _run_binary(native_build, "test_grpc_client")


def test_native_perf_harness(native_build):
    _run_binary(native_build, "test_perf_harness")


@pytest.fixture(scope="module")
def live_server():
    """In-process server with gRPC + HTTP front-ends on ephemeral
    ports, for native integration binaries."""
    from client_tpu.server.app import build_core, start_grpc_server
    from client_tpu.server.http_server import start_http_server_thread

    core = build_core(["simple"])
    grpc_handle = start_grpc_server(core=core)
    http_runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield {
        "grpc": grpc_handle.address,
        "http": "127.0.0.1:%d" % http_runner.port,
    }
    http_runner.stop()
    grpc_handle.stop()


def test_native_http_integration(native_build, live_server):
    _run_binary(
        native_build, "test_http_client",
        {"TPUCLIENT_SERVER_HTTP": live_server["http"]},
    )


def test_native_grpc_integration(native_build, live_server):
    _run_binary(
        native_build, "test_grpc_client",
        {"TPUCLIENT_SERVER_GRPC": live_server["grpc"]},
    )


@pytest.fixture(scope="module")
def serverd_both(native_build):
    """tpu_serverd with both native front-ends, for the C++
    protocol-conformance suite (the typed dual-protocol matrix runs
    against the native server, not the Python one)."""
    import os

    serverd = native_build / "tpu_serverd"
    if not serverd.exists():
        pytest.skip("tpu_serverd not built")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.Popen(
        [str(serverd), "--port", "0", "--http-port", "0",
         "--models", "simple,simple_string,add_sub_fp32,add_sub_large"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=str(REPO), env=env,
    )
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("LISTENING "), line
        http_line = proc.stdout.readline().strip()
        assert http_line.startswith("LISTENING-HTTP "), http_line
        yield {"grpc": "127.0.0.1:%s" % line.split()[1],
               "http": "127.0.0.1:%s" % http_line.split()[1]}
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def test_native_conformance_suite(native_build, serverd_both):
    """The cc_client_test analogue: one typed matrix
    (InferMulti/AsyncInferMulti, BYTES tensors, shm in/out, load with
    config override, client timeout, leak loop, streaming) over BOTH
    native protocol clients against tpu_serverd (parity: reference
    src/c++/tests/cc_client_test.cc:42,300-1350)."""
    _run_binary(
        native_build, "test_conformance",
        {"TPUCLIENT_SERVER_GRPC": serverd_both["grpc"],
         "TPUCLIENT_SERVER_HTTP": serverd_both["http"]},
    )


def test_native_conformance_offline(native_build):
    """Without server envs every case is a gated no-op — the binary
    must still run clean (CI safety)."""
    _run_binary(native_build, "test_conformance")


def test_native_perf_analyzer_openai_e2e(native_build, tmp_path):
    """The native perf_analyzer's openai service-kind: SSE streaming
    against the server's /v1/chat/completions (parity: the reference
    openai client backend)."""
    import json

    from client_tpu.server.app import build_core
    from client_tpu.server.http_server import start_http_server_thread

    binary = native_build / "perf_analyzer"
    assert binary.exists()
    core = build_core(["llm_tiny"])
    runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    try:
        payload = json.dumps({
            "model": "llm_tiny", "max_tokens": 4, "stream": True,
            "messages": [{"role": "user", "content": "bench"}],
        })
        input_file = tmp_path / "openai_input.json"
        input_file.write_text(json.dumps({"data": [{"payload": [payload]}]}))
        export = tmp_path / "profile.json"
        proc = subprocess.run(
            [str(binary), "-m", "llm_tiny",
             "-u", "127.0.0.1:%d" % runner.port,
             "--service-kind", "openai",
             "--endpoint", "v1/chat/completions",
             "--input-data", str(input_file), "--streaming",
             "--concurrency-range", "2", "-p", "800", "-r", "3", "-s", "90",
             "--profile-export-file", str(export)],
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(export.read_text())
        requests = doc["experiments"][0]["requests"]
        assert requests, "no requests recorded"
        # Streaming: every request sees one timestamp per SSE chunk.
        assert any(len(r["response_timestamps"]) > 1 for r in requests)
    finally:
        runner.stop()


def test_native_perf_analyzer_in_process(native_build):
    """--service-kind in_process: the harness embeds CPython and
    drives the server core with NO server process and no RPC (parity:
    the reference's triton_c_api backend, triton_loader.cc:526-690).
    Runs as a subprocess so the embedded interpreter initializes from
    the repo's own tree."""
    import os

    binary = native_build / "perf_analyzer"
    assert binary.exists(), "perf_analyzer not built"
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [str(binary), "-m", "simple", "--service-kind", "in_process",
         "-b", "1", "--concurrency-range", "2", "--async",
         "-p", "400", "-r", "4", "-s", "80"],
        capture_output=True, text=True, timeout=300, env=env, cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "throughput" in proc.stdout
    assert "errors" not in proc.stdout, proc.stdout


def test_native_perf_analyzer_binary_search(native_build, live_server):
    """--binary-search bisects the concurrency range for the highest
    level under the latency threshold (reference
    inference_profiler.h:280-325)."""
    binary = native_build / "perf_analyzer"
    proc = subprocess.run(
        [str(binary), "-m", "simple", "-u", live_server["grpc"],
         "--concurrency-range", "1:8", "--binary-search",
         "-l", "2000",  # generous: everything passes, best = 8
         "-p", "300", "-r", "2", "-s", "90"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # The final (recommendation) row is the highest passing level.
    lines = [line for line in proc.stdout.splitlines()
             if line.startswith("Concurrency:")]
    assert lines, proc.stdout
    assert lines[-1].startswith("Concurrency: 8"), proc.stdout

    # Impossible threshold: fails loudly instead of reporting garbage.
    proc = subprocess.run(
        [str(binary), "-m", "simple", "-u", live_server["grpc"],
         "--concurrency-range", "1:4", "--binary-search",
         "-l", "0.000001", "-p", "200", "-r", "1", "-s", "99"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode != 0
    assert "meets the latency threshold" in proc.stdout + proc.stderr


def test_native_perf_analyzer_request_parameter_and_count(
        native_build, live_server, tmp_path):
    """--request-parameter rides every request; --request-count
    measures exactly one window of N requests; --verbose-csv adds the
    server breakdown columns."""
    binary = native_build / "perf_analyzer"
    csv = tmp_path / "report.csv"
    proc = subprocess.run(
        [str(binary), "-m", "simple", "-u", live_server["grpc"],
         "--concurrency-range", "2",
         "--request-count", "40",
         "--request-parameter", "custom_flag:true:bool",
         "--request-parameter", "custom_level:7:int",
         "-f", str(csv), "--verbose-csv"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # Single-window fixed-count runs are by design, not "unstable".
    assert "did not stabilize" not in proc.stdout, proc.stdout
    header, row = csv.read_text().strip().splitlines()[:2]
    assert "Server Queue us" in header
    assert "Server Inferences" in header
    assert len(row.split(",")) == len(header.split(","))


def test_native_perf_analyzer_json_tensor_format(native_build, live_server):
    """--input-tensor-format json --output-tensor-format json: tensors
    ride as JSON data arrays both ways over HTTP (no binary extension
    anywhere — the interop mode for KServe servers without it; parity:
    the reference's tensor-format flags)."""
    binary = native_build / "perf_analyzer"
    proc = subprocess.run(
        [str(binary), "-m", "simple", "-u", live_server["http"],
         "-i", "http", "--input-tensor-format", "json",
         "--output-tensor-format", "json",
         "--concurrency-range", "2", "--async",
         "-p", "400", "-r", "3", "-s", "50"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "throughput" in proc.stdout


def test_native_perf_analyzer_mpi_degrades_without_launcher(
        native_build, live_server):
    """--enable-mpi outside mpirun must degrade to a clean single-rank
    run (the dlopen'd driver stays inactive without launcher env)."""
    binary = native_build / "perf_analyzer"
    proc = subprocess.run(
        [str(binary), "-m", "simple", "-u", live_server["grpc"],
         "--enable-mpi", "--concurrency-range", "2", "--async",
         "-p", "300", "-r", "2", "-s", "90"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "throughput" in proc.stdout


def test_native_perf_analyzer_mpi_two_ranks(native_build, live_server):
    """Two analyzer ranks under mpirun barrier together and agree on
    stability (rank-merged decision). Skips when the image has no MPI
    launcher (this one ships only the OpenMPI runtime library) — the
    builtin-coordinator test below covers launcher-free 2-rank runs."""
    mpirun = shutil.which("mpirun") or shutil.which("mpiexec")
    if mpirun is None:
        pytest.skip("no MPI launcher on this image — install one (e.g. "
                    "apt install openmpi-bin) to run the 2-rank "
                    "rank-merge test")
    version = subprocess.run([mpirun, "--version"], capture_output=True,
                             text=True).stdout
    # --allow-run-as-root is OpenMPI-only; MPICH's Hydra rejects it.
    root_flags = ["--allow-run-as-root"] if "Open MPI" in version else []
    binary = native_build / "perf_analyzer"
    proc = subprocess.run(
        [mpirun, "-n", "2", *root_flags,
         str(binary), "-m", "simple", "-u", live_server["grpc"],
         "--enable-mpi", "--concurrency-range", "2", "--async",
         "-p", "400", "-r", "3", "-s", "50"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # Both ranks print a report once every rank's windows stabilize.
    assert proc.stdout.count("throughput") >= 2, proc.stdout


def test_native_perf_analyzer_coordinator_two_ranks(
        native_build, live_server):
    """Two analyzer ranks with NO MPI launcher: the builtin TCP
    coordinator (TPUCLIENT_COORDINATOR env contract, the same
    coordinator_address/num_processes/process_id shape as
    jax.distributed.initialize) barriers the ranks together and
    rank-merges the stability decision."""
    import os
    import socket

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    binary = native_build / "perf_analyzer"
    args = [str(binary), "-m", "simple", "-u", live_server["grpc"],
            "--enable-mpi", "--concurrency-range", "2", "--async",
            "-p", "400", "-r", "3", "-s", "50"]
    base_env = dict(
        os.environ,
        TPUCLIENT_COORDINATOR="127.0.0.1:%d" % port,
        TPUCLIENT_WORLD_SIZE="2",
        TPUCLIENT_COORD_TIMEOUT_S="60",
    )
    procs = [
        subprocess.Popen(args, env=dict(base_env, TPUCLIENT_RANK=str(r)),
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True)
        for r in range(2)
    ]
    try:
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, out + err
            # No degrade warning: the collectives stayed up for the
            # whole profile, so the decision really was rank-merged.
            assert "degrading to rank-local" not in err, err
            outs.append(out)
        for out in outs:
            assert "throughput" in out, out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


def test_native_perf_analyzer_ranks_flag(native_build, live_server,
                                         tmp_path):
    """--ranks 2 forks a second local rank over the builtin
    coordinator (launcher-free `mpirun -n 2`): one invocation, two
    rank-merged reports, per-rank export files (rank 0 keeps the
    given name; peers get a .rankN suffix instead of clobbering)."""
    binary = native_build / "perf_analyzer"
    export = tmp_path / "profile.json"
    proc = subprocess.run(
        [str(binary), "-m", "simple", "-u", live_server["grpc"],
         "--ranks", "2", "--concurrency-range", "2", "--async",
         "-p", "400", "-r", "3", "-s", "50",
         "--profile-export-file", str(export)],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.count("throughput") >= 2, proc.stdout
    assert "degrading to rank-local" not in proc.stderr, proc.stderr
    assert export.exists()
    assert (tmp_path / "profile.json.rank1").exists()


@pytest.mark.parametrize("distribution", ["constant", "poisson"])
def test_native_perf_analyzer_request_rate_e2e(
        native_build, live_server, distribution):
    """--request-rate-range end to end in both distributions (parity:
    the reference's request-rate mode runs)."""
    binary = native_build / "perf_analyzer"
    proc = subprocess.run(
        [str(binary), "-m", "simple", "-u", live_server["grpc"],
         "--request-rate-range", "100", "--async",
         "--request-distribution", distribution,
         "-p", "600", "-r", "2", "-s", "90"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Request rate: 100" in proc.stdout, proc.stdout
    assert "throughput" in proc.stdout


def test_native_perf_analyzer_custom_intervals_e2e(
        native_build, live_server, tmp_path):
    """--request-intervals end to end: the measured request count
    follows the replayed schedule (parity: CustomLoadManager)."""
    binary = native_build / "perf_analyzer"
    intervals = tmp_path / "intervals.txt"
    intervals.write_text("5000\n5000\n10000\n")  # ~150 req/s cycle
    proc = subprocess.run(
        [str(binary), "-m", "simple", "-u", live_server["grpc"],
         "--request-intervals", str(intervals), "--async",
         "-p", "600", "-r", "2", "-s", "90"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "throughput" in proc.stdout


def test_native_perf_analyzer_periodic_concurrency_e2e(
        native_build, live_server, tmp_path):
    """--periodic-concurrency-range ramp end to end with a profile
    export covering the whole ramp (parity:
    periodic_concurrency_manager.cc + its profile-export contract)."""
    binary = native_build / "perf_analyzer"
    export = tmp_path / "ramp_export.json"
    proc = subprocess.run(
        [str(binary), "-m", "simple", "-u", live_server["grpc"],
         "--periodic-concurrency-range", "1:4:1",
         "--request-period", "8", "--async",
         "--profile-export-file", str(export)],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json

    doc = json.loads(export.read_text())
    requests = doc["experiments"][0]["requests"]
    # Three intermediate levels x request_period, plus the top level.
    assert len(requests) >= 24, len(requests)


@pytest.mark.parametrize("mode", ["--async", "--sync"])
@pytest.mark.parametrize("algorithm", ["gzip", "deflate"])
def test_native_perf_analyzer_grpc_compression(
        native_build, live_server, algorithm, mode):
    """--grpc-compression-algorithm: request messages ride the gRPC
    wire compressed (flag-1 frames + grpc-encoding); the grpcio server
    decompresses natively, so an erroring run would prove a framing
    bug."""
    binary = native_build / "perf_analyzer"
    proc = subprocess.run(
        [str(binary), "-m", "simple", "-u", live_server["grpc"],
         "--concurrency-range", "2", mode,
         "--grpc-compression-algorithm", algorithm,
         "-p", "300", "-r", "2", "-s", "90"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "throughput" in proc.stdout
    assert "errors" not in proc.stdout, proc.stdout


@pytest.mark.parametrize("shm", ["none", "system", "tpu"])
def test_native_perf_analyzer_e2e(native_build, live_server, shm):
    """The native perf_analyzer binary end-to-end against the live
    server, in every shared-memory mode (parity: the reference's
    perf_analyzer L0 runs)."""
    binary = native_build / "perf_analyzer"
    assert binary.exists(), "perf_analyzer not built"
    proc = subprocess.run(
        [str(binary), "-m", "simple", "-u", live_server["grpc"],
         "--concurrency-range", "2", "-p", "400", "-r", "4", "-s", "80",
         "--shared-memory", shm],
        capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "throughput" in proc.stdout
