"""Flash-attention kernel vs dense attention. Runs in Pallas
interpreter mode on the CPU test platform (bit-accurate semantics of
the kernel without TPU hardware); the bench exercises the compiled
path on the real chip."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from client_tpu.ops import flash_attention  # noqa: E402


def dense_attention(q, k, v, causal):
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    logits = jnp.einsum("bshd,bthd->bhst",
                        q.astype(jnp.float32), k.astype(jnp.float32))
    logits = logits / (d ** 0.5)
    if causal:
        mask = np.tril(np.ones((s_q, s_k), bool))
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bthd->bshd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)


def _rand(shape, seed):
    return np.random.default_rng(seed).standard_normal(shape).astype(
        np.float32)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("s", [128, 256])
def test_flash_matches_dense(causal, s):
    q = jnp.asarray(_rand((2, s, 4, 32), 0))
    k = jnp.asarray(_rand((2, s, 4, 32), 1))
    v = jnp.asarray(_rand((2, s, 4, 32), 2))
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    expected = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_flash_unpadded_vs_padded_lengths():
    """Sequence not a multiple of the block: padded key rows must not
    leak into the output."""
    s = 192  # 1.5 blocks of 128
    q = jnp.asarray(_rand((1, s, 2, 64), 3))
    k = jnp.asarray(_rand((1, s, 2, 64), 4))
    v = jnp.asarray(_rand((1, s, 2, 64), 5))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    expected = dense_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_flash_cross_attention_shapes():
    """Non-causal with S_q != S_k (cross attention)."""
    q = jnp.asarray(_rand((1, 64, 2, 32), 6))
    k = jnp.asarray(_rand((1, 200, 2, 32), 7))
    v = jnp.asarray(_rand((1, 200, 2, 32), 8))
    out = flash_attention(q, k, v, causal=False, interpret=True)
    expected = dense_attention(q, k, v, False)
    assert out.shape == (1, 64, 2, 32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_flash_outlier_masked_logit_no_nan():
    q = _rand((1, 128, 2, 32), 9)
    k = _rand((1, 128, 2, 32), 10)
    q[0, 0] = 40.0
    k[0, 127] = 40.0  # future key aligned with the first query
    v = _rand((1, 128, 2, 32), 11)
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=True, interpret=True)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow  # compiles the LLM forward with the pallas kernel
def test_flash_llm_forward_hook():
    """The LLM scoring forward with the flash hook matches dense."""
    from client_tpu.models.llm import (
        LlmConfig,
        forward,
        init_params,
    )
    from client_tpu.ops import flash_attention_fn

    cfg = LlmConfig(d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                    d_ff=128, max_seq=64, dtype="float32")
    params = init_params(jax.random.PRNGKey(1), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab, (2, 48)),
        jnp.int32)
    dense = forward(params, tokens, cfg)
    flash = forward(params, tokens, cfg,
                    attention_fn=flash_attention_fn(interpret=True))
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=2e-3, atol=2e-3)


def test_flash_variable_valid_lengths():
    """Per-sequence key masking (the BERT variable-length-batch shape):
    each batch row attends only its own valid prefix."""
    b, s, h, d = 3, 128, 2, 32
    q = jnp.asarray(_rand((b, s, h, d), 20))
    k = jnp.asarray(_rand((b, s, h, d), 21))
    v = jnp.asarray(_rand((b, s, h, d), 22))
    lengths = np.array([128, 70, 9], dtype=np.int32)
    out = flash_attention(q, k, v, causal=False,
                          valid_lengths=lengths, interpret=True)
    # dense reference with per-row key masks
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / (d ** 0.5)
    key_ok = np.arange(s)[None, :] < lengths[:, None]        # [B,T]
    logits = jnp.where(key_ok[:, None, None, :], logits, -jnp.inf)
    expected = jnp.einsum("bhst,bthd->bshd",
                          jax.nn.softmax(logits, axis=-1),
                          v.astype(jnp.float32))
    # rows whose queries sit beyond their own valid length still get
    # finite output (they attend the valid prefix)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)
