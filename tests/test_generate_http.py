"""HTTP generate + generate_stream (SSE) endpoint tests — the LLM
serving surface genai benchmarks drive."""

import json

import numpy as np
import pytest

from client_tpu.models.llm import LlmConfig, LlmModel
from client_tpu.server.app import build_core
from client_tpu.server.http_server import start_http_server_thread

TINY = LlmConfig(d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                 d_ff=128, max_seq=128)


@pytest.fixture(scope="module")
def http_server():
    core = build_core([])
    core.repository.add_model(LlmModel(name="llm_test", cfg=TINY),
                              warmup=True)
    runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield runner
    runner.stop()


def _post(port, path, body):
    import http.client as hc

    conn = hc.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", path, body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    response = conn.getresponse()
    payload = response.read()
    conn.close()
    return response.status, payload


def test_generate(http_server):
    status, payload = _post(http_server.port,
                            "/v2/models/llm_test/generate",
                            {"text_input": "hello", "max_tokens": 4,
                             "ignore_eos": True})
    assert status == 200
    doc = json.loads(payload)
    assert doc["model_name"] == "llm_test"
    assert "text_output" in doc


def test_generate_unknown_model(http_server):
    status, payload = _post(http_server.port, "/v2/models/ghost/generate",
                            {"text_input": "x"})
    assert status == 404


def test_generate_stream_sse(http_server):
    status, payload = _post(http_server.port,
                            "/v2/models/llm_test/generate_stream",
                            {"text_input": "hello", "max_tokens": 4,
                             "ignore_eos": True})
    assert status == 200
    events = [
        json.loads(line[len("data: "):])
        for line in payload.decode().split("\n")
        if line.startswith("data: ")
    ]
    assert 1 <= len(events) <= 4
    for event in events:
        assert "text_output" in event
