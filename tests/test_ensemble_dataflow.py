"""Device-resident ensemble dataflow (ISSUE 16): golden parity vs the
legacy host-mediated arm, span shape (per-stage ensemble_step chain,
zero interior relay_fetch), composing-cache subgraph short-circuit,
replica fault masking mid-ensemble, mixed ensemble+standalone fusion
into one batch, and Triton-parity per-stage statistics.

Uses tiny custom composing models (2 ms backbone) so the file stays
tier-1 fast; the row-proportional A/B pair lives in the bench/smoke
driver (client_tpu.perf.bench_child.run_ensemble_dataflow_measure).
"""

import json
import re
import threading
import time

import numpy as np
import pytest

from client_tpu._infer_common import InferInput
from client_tpu.grpc._utils import get_inference_request
from client_tpu.models.ensemble import EnsembleModel
from client_tpu.server import chaos
from client_tpu.server.app import build_core
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.utils import InferenceServerException


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.configure(None)
    yield
    chaos.configure(None)


# -- tiny composing graph --------------------------------------------------


class _Edge(ServedModel):
    """Direct (scheduler-less) first stage: H = XIN * 2."""

    max_batch_size = 8

    def __init__(self, name="dfl_edge"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("XIN", "FP32", [4])]
        self.outputs = [TensorSpec("H", "FP32", [4])]

    def infer(self, inputs, parameters=None):
        x = np.asarray(inputs["XIN"], dtype=np.float32)
        return {"H": x * np.float32(2.0)}


class _Mid(ServedModel):
    """Batched, cached backbone: F = H + 1. ``calls`` counts
    executions on this instance — the cache-short-circuit probe."""

    max_batch_size = 8
    dynamic_batching = True
    preferred_batch_sizes = [2, 4, 8]
    max_queue_delay_us = 50_000
    response_cache = True

    def __init__(self, name="dfl_mid"):
        super().__init__()
        self.name = name
        self.calls = 0
        self.inputs = [TensorSpec("H", "FP32", [4])]
        self.outputs = [TensorSpec("F", "FP32", [4])]

    def infer(self, inputs, parameters=None):
        self.calls += 1
        time.sleep(0.002)  # real compute time for the stats gate
        x = np.asarray(inputs["H"], dtype=np.float32)
        return {"F": x + np.float32(1.0)}


class _MidReplicated(_Mid):
    """Two fault domains, cache off so every request executes (chaos
    must hit the model, not a cache hit)."""

    instance_group_count = 2
    response_cache = False
    max_queue_delay_us = 5_000

    def __init__(self, name="dfl_mid_r"):
        super().__init__(name=name)


class _Tail(ServedModel):
    """Direct reduction at the graph edge: OUT = sum(F)."""

    max_batch_size = 8

    def __init__(self, name="dfl_tail"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("F", "FP32", [4])]
        self.outputs = [TensorSpec("OUT", "FP32", [1])]

    def infer(self, inputs, parameters=None):
        x = np.asarray(inputs["F"], dtype=np.float32)
        return {"OUT": x.sum(axis=-1, keepdims=True)}


def _make_ensemble(repository, name, mid="dfl_mid", legacy=False):
    ensemble = EnsembleModel(
        name=name,
        repository=repository,
        steps=[
            ("dfl_edge", {"XIN": "XIN"}, {"h": "H"}),
            (mid, {"h": "H"}, {"f": "F"}),
            ("dfl_tail", {"f": "F"}, {"OUT": "OUT"}),
        ],
        inputs=[TensorSpec("XIN", "FP32", [4])],
        outputs=[TensorSpec("OUT", "FP32", [1])],
        max_batch_size=8,
    )
    ensemble.device_dataflow = not legacy
    return ensemble


@pytest.fixture(scope="module")
def core():
    core = build_core([], warmup=False)
    repo = core.repository
    repo.add_factory("dfl_edge", _Edge)
    repo.add_factory("dfl_mid", _Mid)
    repo.add_factory("dfl_mid_r", _MidReplicated)
    repo.add_factory("dfl_tail", _Tail)
    repo.add_factory("dfl_ens", lambda: _make_ensemble(repo, "dfl_ens"))
    repo.add_factory(
        "dfl_ens_legacy",
        lambda: _make_ensemble(repo, "dfl_ens_legacy", legacy=True))
    repo.add_factory(
        "dfl_ens_r",
        lambda: _make_ensemble(repo, "dfl_ens_r", mid="dfl_mid_r"))
    for name in ("dfl_ens", "dfl_ens_legacy", "dfl_ens_r"):
        core.load_model(name, warmup=False)
    yield core
    core.shutdown()


def _request(model, seed, tensor="XIN"):
    data = ((np.arange(4, dtype=np.float32) + 1.0)
            * np.float32(seed)).reshape(1, 4)
    inp = InferInput(tensor, [1, 4], "FP32")
    inp.set_data_from_numpy(data)
    return get_inference_request(model_name=model, inputs=[inp],
                                 outputs=None)


def _stats(core, name):
    return core.model_statistics(name).model_stats[0]


def _family_value(core, family, model):
    pattern = r'%s\{model="%s"\} (\d+)' % (family, model)
    match = re.search(pattern, core.metrics_text())
    return int(match.group(1)) if match else 0


# -- parity ----------------------------------------------------------------


def test_golden_parity_dataflow_vs_legacy(core):
    for seed in (3, 5, 11, 42):
        dataflow = core.infer(_request("dfl_ens", seed))
        legacy = core.infer(_request("dfl_ens_legacy", seed))
        assert dataflow.raw_output_contents[0] \
            == legacy.raw_output_contents[0]
        value = np.frombuffer(dataflow.raw_output_contents[0],
                              np.float32)
        expected = (np.arange(4, dtype=np.float32) + 1.0) * seed
        np.testing.assert_allclose(
            value, [(expected * 2.0 + 1.0).sum()], rtol=1e-6)


# -- span shape ------------------------------------------------------------


def test_span_tree_has_step_chain_and_no_interior_relay_fetch(
        core, tmp_path):
    path = tmp_path / "trace.jsonl"
    keys = ("trace_level", "trace_rate", "trace_count",
            "log_frequency", "trace_file", "trace_mode")
    core.trace_setting("dfl_ens", {
        "trace_level": ["TIMESTAMPS"], "trace_rate": ["1"],
        "trace_count": ["-1"], "log_frequency": ["1"],
        "trace_file": [str(path)], "trace_mode": ["compact"]})
    try:
        core.infer(_request("dfl_ens", 21))
    finally:
        core.trace_setting("dfl_ens", {key: [] for key in keys})
    records = [json.loads(line) for line in open(path)
               if line.strip()]
    assert records
    names = [s["name"] for s in records[0]["spans"]]
    steps = [s for s in records[0]["spans"]
             if s["name"] == "ensemble_step"]
    # One span per composing stage, labeled <index>:<model> ...
    assert [s["attrs"]["step"] for s in steps] \
        == ["0:dfl_edge", "1:dfl_mid", "2:dfl_tail"]
    # ... and ZERO host round-trips between stages: no relay_fetch
    # span anywhere in the request's tree.
    assert "relay_fetch" not in names


# -- composing-cache short-circuit ----------------------------------------


def test_composing_cache_short_circuits_subgraph(core):
    mid = core.repository.load("dfl_mid")
    seed = 77
    first = core.infer(_request("dfl_ens", seed)).raw_output_contents[0]
    hits_before = _family_value(core, "tpu_ensemble_cache_hits_total",
                                "dfl_ens")
    # The stage insert is async (single-worker pool); poll until a
    # repeat stops executing the backbone.
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        calls_before = mid.calls
        repeat = core.infer(
            _request("dfl_ens", seed)).raw_output_contents[0]
        assert repeat == first
        if mid.calls == calls_before:
            break
        time.sleep(0.05)
    else:
        pytest.fail("repeat requests kept executing the cached "
                    "backbone stage")
    assert _family_value(core, "tpu_ensemble_cache_hits_total",
                         "dfl_ens") > hits_before
    # The composing model's own Triton-parity cache counters see the
    # short-circuit too.
    assert _stats(core, "dfl_mid").inference_stats.cache_hit.count > 0


# -- replica fault masking mid-ensemble ------------------------------------


def test_replica_kill_masked_mid_ensemble(core):
    errors = [0]
    chaos.configure(chaos.ChaosConfig(error_rate=1.0,
                                      replica="dfl_mid_r:1"))
    try:
        def loop(index):
            for i in range(10):
                try:
                    core.infer(_request("dfl_ens_r",
                                        1000 + index * 100 + i))
                except InferenceServerException:
                    errors[0] += 1

        pool = [threading.Thread(target=loop, args=(i,))
                for i in range(4)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
    finally:
        chaos.configure(None)
    # Blast radius is ONE fault domain of the composing model: zero
    # client-visible ensemble errors, faults masked by redispatch.
    assert errors[0] == 0
    entry = _stats(core, "dfl_mid_r")
    ejected = sum(int(r.ejected_count) for r in entry.replica_stats)
    redispatched = _family_value(core, "tpu_replica_redispatch_total",
                                 "dfl_mid_r")
    assert ejected + redispatched >= 1
    assert core.model_ready("dfl_ens_r")


# -- mixed ensemble + standalone fusion ------------------------------------


def test_ensemble_and_standalone_fuse_into_one_batch(core):
    before = _stats(core, "dfl_mid")
    inf0, exec0 = int(before.inference_count), int(before.execution_count)
    barrier = threading.Barrier(2)
    failures = []

    def ensemble_request():
        barrier.wait()
        try:
            core.infer(_request("dfl_ens", 901))
        except Exception as exc:  # noqa: BLE001
            failures.append(exc)

    def standalone_request():
        barrier.wait()
        try:
            core.infer(_request("dfl_mid", 902, tensor="H"))
        except Exception as exc:  # noqa: BLE001
            failures.append(exc)

    pool = [threading.Thread(target=ensemble_request),
            threading.Thread(target=standalone_request)]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()
    assert not failures
    after = _stats(core, "dfl_mid")
    # Two inference rows (one interior dataflow step + one standalone
    # wire request), ONE fused execution: the shared backbone gathered
    # both into a single batch (preferred size 2 dispatches the moment
    # the second member arrives, inside the 50 ms window).
    assert int(after.inference_count) - inf0 == 2
    assert int(after.execution_count) - exec0 == 1


# -- per-stage statistics parity -------------------------------------------


def test_composing_stats_keep_queue_and_compute_accounting(core):
    before = _stats(core, "dfl_mid")
    core.infer(_request("dfl_ens", 511))
    after = _stats(core, "dfl_mid")
    # PR-1 histogram fields stay meaningful for composing traffic:
    # the row count, the fused-execution count, a real queue wait
    # (the batcher's gather window) and a real compute time (the
    # 2 ms backbone) all advance.
    assert int(after.inference_count) - int(before.inference_count) == 1
    assert int(after.execution_count) - int(before.execution_count) == 1
    stats_b, stats_a = before.inference_stats, after.inference_stats
    assert int(stats_a.success.count) > int(stats_b.success.count)
    assert int(stats_a.queue.ns) > int(stats_b.queue.ns)
    assert int(stats_a.compute_infer.ns) - int(stats_b.compute_infer.ns) \
        >= 1_000_000  # >= half the 2 ms sleep, well clear of zero
    # The ensemble itself keeps end-to-end accounting as well.
    assert _stats(core, "dfl_ens").inference_stats.success.count > 0
