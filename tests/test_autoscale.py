"""Autoscale controller tests (client_tpu.server.autoscale).

Covers the PR-17 tentpole end to end with a hand-driven control loop
(the background thread is stopped so every test tick is
deterministic): queue-pressure scale-up through the canaried
admission path, quiet scale-down through the routing-tail drain, the
scale-to-zero round trip (HBM ledger rows release, cold start answers
503 + honest Retry-After, then serves), canary rejection of a
chaos-poisoned prospect without disturbing serving, the
admission-coupled shed directive, the chaos OverloadScenario
diurnal-trace mode, and the /v2/debug ``controller`` section +
flight-ring decision records the acceptance criteria audit."""

import threading
import time

import numpy as np
import pytest

from client_tpu._infer_common import InferInput
from client_tpu.grpc._utils import get_inference_request
from client_tpu.models.add_sub import AddSub
from client_tpu.server import chaos
from client_tpu.server import devstats as devstats_mod
from client_tpu.server import flight as flightrec
from client_tpu.server import qos
from client_tpu.server.app import build_core
from client_tpu.utils import InferenceServerException


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.configure(None)
    yield
    chaos.configure(None)


def _request(value, model, shape=(1, 16), **kwargs):
    tensors = []
    for name, fill in (("INPUT0", value), ("INPUT1", 2 * value)):
        tensor = InferInput(name, list(shape), "INT32")
        tensor.set_data_from_numpy(np.full(shape, fill, dtype=np.int32))
        tensors.append(tensor)
    return get_inference_request(model_name=model, inputs=tensors,
                                 outputs=None, **kwargs)


def _wait_for(predicate, timeout_s=8.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


def _slow_autoscale_factory(name, delay_s=0.02, max_replicas=3):
    def factory():
        model = AddSub(name=name, datatype="INT32", shape=(16,))
        model.max_batch_size = 4
        model.dynamic_batching = True
        model.preferred_batch_sizes = [4]
        model.max_queue_delay_us = 500
        model.max_queue_size = 64
        model.instance_group_count = 1
        model.instance_group_kind = "cpu"
        model.replica_failure_threshold = 3
        model.replica_recovery_s = 0.5
        model.autoscale_min_replicas = 1
        model.autoscale_max_replicas = max_replicas
        model.autoscale_interval_s = 0.05
        model.autoscale_queue_high = 1.0
        model.autoscale_up_cooldown_s = 0.0
        model.autoscale_down_cooldown_s = 0.0

        original_infer = model.infer

        def slow_infer(inputs, parameters=None):
            time.sleep(delay_s)
            return original_infer(inputs, parameters)

        model.infer = slow_infer
        return model
    return factory


# -- config plumbing -------------------------------------------------------


def test_autoscale_block_renders_in_config_pb():
    core = build_core(["simple_autoscale"], warmup=False)
    try:
        config = core.repository.get("simple_autoscale").config_pb()
        auto = config.instance_group[0].autoscale
        assert auto.max_replicas == 4
        assert auto.min_replicas == 1
        assert auto.queue_high == 2.0
        # The controller thread started lazily because an autoscale-
        # enabled model was loaded.
        assert core.autoscaler._thread is not None
    finally:
        core.shutdown()


# -- the feedback loop -----------------------------------------------------


def test_scale_up_under_pressure_then_down_when_quiet():
    core = build_core([], warmup=False)
    try:
        core.repository.add_factory(
            "slow_autoscale", _slow_autoscale_factory("slow_autoscale"))
        core.load_model("slow_autoscale", warmup=False)
        core.autoscaler.stop()  # hand-driven ticks from here on
        core.infer(_request(0, "slow_autoscale"))
        replica_set = core._replica_sets["slow_autoscale"]
        assert replica_set.count == 1

        stop = threading.Event()

        def flood(index):
            i = 0
            while not stop.is_set():
                try:
                    core.infer(_request(index * 10_000 + i,
                                        "slow_autoscale"))
                except InferenceServerException:
                    pass
                i += 1

        pool = [threading.Thread(target=flood, args=(i,), daemon=True)
                for i in range(8)]
        for thread in pool:
            thread.start()
        try:
            # Pressure ticks: queue depth per healthy replica exceeds
            # queue_high, so each tick (cooldown 0) admits one
            # canaried replica until the backlog drains or max is hit.
            grown = _wait_for(
                lambda: core.autoscaler.tick_once() is not None
                and replica_set.count >= 2)
            assert grown, "controller never scaled up under backlog"
        finally:
            stop.set()
            for thread in pool:
                thread.join(timeout=5)

        snapshot = core.autoscaler.snapshot()["slow_autoscale"]
        assert any(key.startswith("up|")
                   for key in snapshot["events"])

        # Quiet: empty queue, burn 0 -> drain back to min_replicas.
        shrunk = _wait_for(
            lambda: core.autoscaler.tick_once() is not None
            and replica_set.count == 1)
        assert shrunk, "controller never drained back to the floor"
        snapshot = core.autoscaler.snapshot()["slow_autoscale"]
        assert any(key.startswith("down|")
                   for key in snapshot["events"])
        # Serving is undisturbed after the full up/down cycle.
        core.infer(_request(7, "slow_autoscale"))
        # Every decision left an auditable flight-ring record.
        decisions = [r for r in core.flight.snapshot("slow_autoscale")
                     if r.get("reason") == "decision"]
        assert any("autoscale_up" in r["decision"] for r in decisions)
        assert any("autoscale_down" in r["decision"] for r in decisions)
    finally:
        core.shutdown()


def test_scale_to_zero_round_trip():
    core = build_core([], warmup=False)
    try:
        factory = _slow_autoscale_factory("zero_autoscale", delay_s=0.0)

        def zero_factory():
            model = factory()
            model.autoscale_min_replicas = 0
            model.autoscale_idle_s = 0.2
            return model

        core.repository.add_factory("zero_autoscale", zero_factory)
        core.load_model("zero_autoscale", warmup=False)
        core.autoscaler.stop()
        core.infer(_request(0, "zero_autoscale"))
        ledger = devstats_mod.get().ledger

        # Idle past idle_s -> the controller unloads the model whole.
        drained = _wait_for(
            lambda: core.autoscaler.tick_once() is not None
            and not core.repository.is_ready("zero_autoscale"))
        assert drained, "idle model never scaled to zero"
        # The HBM ledger shows exactly whose memory freed: no rows
        # remain for the model (tpu_hbm_model_bytes drops to 0).
        assert ledger.model_bytes("zero_autoscale") == {}
        assert core.autoscaler.snapshot()["zero_autoscale"]["cold"]

        # First arrival: an honest 503 + Retry-After while warming.
        with pytest.raises(InferenceServerException) as raised:
            core.infer(_request(1, "zero_autoscale"))
        assert raised.value.status() == "UNAVAILABLE"
        assert getattr(raised.value, "retry_after_s", 0) > 0
        assert "cold-starting" in str(raised.value)

        # ... then the background reload finishes and serving resumes.
        assert _wait_for(
            lambda: core.repository.is_ready("zero_autoscale"))
        core.infer(_request(2, "zero_autoscale"))
        # The model turns ready inside the cold-start thread a beat
        # before that thread stamps its decision — wait for the event
        # instead of racing the stamp.
        assert _wait_for(
            lambda: core.autoscaler.snapshot()["zero_autoscale"]
            ["events"].get("up|cold_start") == 1)
        events = core.autoscaler.snapshot()["zero_autoscale"]["events"]
        assert events.get("down|scale_to_zero") == 1
        decisions = [r["decision"] for r
                     in core.flight.snapshot("zero_autoscale")
                     if r.get("reason") == "decision"]
        assert "autoscale_down reason=scale_to_zero" in decisions
        assert "autoscale_up reason=cold_start" in decisions
    finally:
        core.shutdown()


def test_canary_rejects_sick_replica_without_disturbing_serving():
    core = build_core([], warmup=False)
    try:
        core.repository.add_factory(
            "canary_autoscale",
            _slow_autoscale_factory("canary_autoscale", delay_s=0.0))
        core.load_model("canary_autoscale", warmup=False)
        core.autoscaler.stop()
        core.infer(_request(0, "canary_autoscale"))
        replica_set = core._replica_sets["canary_autoscale"]

        # Poison the index the NEXT replica will get: the chaos fault
        # fires inside the canary probe (the chaos-injected execution
        # path), so the prospect never enters routing.
        sick_index = replica_set._next_index
        chaos.configure(chaos.ChaosConfig(
            error_rate=1.0,
            replica="canary_autoscale:%d" % sick_index))
        assert replica_set.scale_up() is False
        assert replica_set.count == 1
        assert replica_set.canary_rejects == 1
        assert all(r.index != sick_index
                   for r in replica_set.replicas)
        # Serving through the existing fleet is untouched (the chaos
        # scope targets only the rejected index).
        core.infer(_request(1, "canary_autoscale"))
        chaos.configure(None)
        # The same grow succeeds once the fault clears — indexes are
        # never reused, so the retry canaries a FRESH index.
        assert replica_set.scale_up() is True
        assert replica_set.count == 2
    finally:
        core.shutdown()


# -- admission-coupled shedding --------------------------------------------


def test_shed_directive_sheds_lowest_class_with_controller_retry_after():
    core = build_core(["simple_autoscale"], warmup=False)
    try:
        core.autoscaler.stop()
        core.infer(_request(0, "simple_autoscale"))
        batcher = core._batchers["simple_autoscale"]
        directive = qos.ShedDirective(active=True, retry_after_s=2.5,
                                      reason="test directive",
                                      since=time.time())
        batcher.set_shed_directive(directive)
        # Lowest class (the default, 2) sheds at the door with the
        # controller's predicted recovery as Retry-After ...
        with pytest.raises(InferenceServerException) as raised:
            core.infer(_request(1, "simple_autoscale"))
        assert raised.value.status() == "UNAVAILABLE"
        assert raised.value.retry_after_s == 2.5
        assert "autoscale directive" in str(raised.value)
        # ... while priority 1 is admitted normally.
        core.infer(_request(2, "simple_autoscale", priority=1))
        batcher.set_shed_directive(None)
        core.infer(_request(3, "simple_autoscale"))
    finally:
        core.shutdown()


def test_controller_installs_and_clears_directive_on_verdict():
    core = build_core([], warmup=False)
    try:
        core.repository.add_factory(
            "shed_autoscale",
            _slow_autoscale_factory("shed_autoscale", delay_s=0.0,
                                    max_replicas=1))
        core.load_model("shed_autoscale", warmup=False)
        core.autoscaler.stop()
        core.infer(_request(0, "shed_autoscale"))
        batcher = core._batchers["shed_autoscale"]
        verdicts = {"shed_autoscale": {
            "healthy": False, "monitored": True,
            "burn": {"fast": 4.0, "slow": 2.0},
        }}
        core.slo.cached_verdicts = lambda max_age_s=1.0: verdicts
        # Unhealthy at max scale (1 of 1): growing is impossible, so
        # the controller feeds the shed directive into admission.
        core.autoscaler.tick_once()
        installed = batcher.shed_directive()
        assert installed is not None and installed.active
        assert installed.retry_after_s > 0
        state = core.autoscaler.snapshot()["shed_autoscale"]
        assert state["shed"]["active"]
        assert state["events"].get("shed|slo_unmeetable") == 1
        # Recovery clears it the next tick.
        verdicts["shed_autoscale"]["healthy"] = True
        core.autoscaler.tick_once()
        assert batcher.shed_directive() is None
        state = core.autoscaler.snapshot()["shed_autoscale"]
        assert not state["shed"]["active"]
        assert state["events"].get("shed_clear|slo_recovered") == 1
    finally:
        core.shutdown()


# -- chaos diurnal trace ---------------------------------------------------


def test_overload_trace_spec_parses():
    kwargs = chaos.OverloadScenario.parse_spec(
        "trace=50:2+500:3+0:1,repeat=2,workers=4,seed=3")
    assert kwargs["trace"] == [(50.0, 2.0), (500.0, 3.0), (0.0, 1.0)]
    assert kwargs["repeat"] == 2
    assert kwargs["workers"] == 4
    with pytest.raises(ValueError):
        chaos.OverloadScenario.parse_spec("trace=50:2+bogus")
    with pytest.raises(ValueError):
        chaos.OverloadScenario.parse_spec("cadence=5")


def test_overload_trace_replays_schedule():
    stamps = []
    lock = threading.Lock()

    def submit():
        with lock:
            stamps.append(time.monotonic())

    scenario = chaos.OverloadScenario(
        submit, workers=2, seed=7,
        trace=[(200.0, 0.25), (0.0, 0.35), (200.0, 0.25)], repeat=1)
    start = time.monotonic()
    scenario.start()
    assert scenario.finished.wait(5.0)
    scenario.stop()
    assert scenario.stats()["submitted"] == len(stamps)
    assert len(stamps) > 0
    # The idle stage really is idle: no arrivals land in its middle
    # (stage 1 ends by 0.25 + generous scheduler slack; stage 3 does
    # not begin before 0.60 on any worker).
    gap = [t - start for t in stamps if 0.35 < t - start < 0.55]
    assert gap == []


# -- observability ---------------------------------------------------------


def test_debug_controller_section_and_desired_metric():
    core = build_core(["simple_autoscale"], warmup=False)
    try:
        core.autoscaler.stop()
        core.infer(_request(0, "simple_autoscale"))
        core.autoscaler.tick_once()
        section = core.debug_snapshot()["controller"]
        entry = section["simple_autoscale"]
        assert entry["actual"] == 1
        assert entry["desired"] >= 1
        assert {"last_decision", "last_reason", "replica_seconds",
                "events", "shed", "cold"} <= set(entry)
        text = core.metrics_text()
        assert 'tpu_replica_desired{model="simple_autoscale"}' in text
        assert 'tpu_replica_seconds_total{model="simple_autoscale"}' \
            in text
    finally:
        core.shutdown()


def test_flight_record_decision_populates_empty_ring():
    recorder = flightrec.FlightRecorder()
    # mark_incident on an empty ring stamps nothing — the reason
    # record_decision exists: a scaling decision must be auditable
    # even when no request trace happened to be resident around it.
    assert recorder.mark_incident("fresh_model", "autoscale_up") == 0
    assert recorder.record_decision(
        "fresh_model", "autoscale_up reason=queue_depth",
        {"from": 1, "to": 2})
    records = recorder.snapshot("fresh_model")
    assert len(records) == 1
    assert records[0]["reason"] == "decision"
    assert records[0]["decision"] == "autoscale_up reason=queue_depth"
    assert records[0]["attrs"] == {"from": 1, "to": 2}
