"""End-to-end span tracing: settings semantics, golden span trees for
every scheduler path, W3C trace-context propagation from all four
clients, and request-id correlation (PR 6).

One core serves BOTH transports so trace settings/records can be
asserted against the same sampling state regardless of which front-end
carried the request.
"""

import asyncio
import json
import logging
import threading

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
import client_tpu.http as httpclient
from client_tpu._infer_common import InferInput
from client_tpu.grpc._utils import get_inference_request
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.http_server import start_http_server_thread
from client_tpu.tracing import ClientTracer, format_traceparent, parse_traceparent
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def stack():
    core = build_core(["simple", "simple_cache", "add_sub_fp32",
                       "dyna_sequence", "repeat_int32"])
    grpc_handle = start_grpc_server(core=core, address="127.0.0.1:0")
    http_runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    yield {"core": core, "grpc": grpc_handle.address,
           "http": "127.0.0.1:%d" % http_runner.port}
    # stop() flips ready + shuts the core down; the runner rides along.
    http_runner.stop()
    grpc_handle.stop()


@pytest.fixture()
def core(stack):
    yield stack["core"]
    # Leave tracing off between tests, whatever a test configured.
    stack["core"].trace_setting("", {"trace_level": ["OFF"]})
    stack["core"].trace_setting("simple", {"trace_level": []})


def _enable(core, path, model="", rate=1, count=-1, freq=1,
            mode="compact"):
    core.trace_setting(model or "", {
        "trace_level": ["TIMESTAMPS"], "trace_rate": [str(rate)],
        "trace_count": [str(count)], "log_frequency": [str(freq)],
        "trace_file": [str(path)], "trace_mode": [mode]})


def _records(path):
    out = []
    for line in open(path):
        line = line.strip()
        if line:
            out.append(json.loads(line))
    return out


def _request(model="simple", seed=0, batched=False, request_id="",
             sequence_id=0, sequence_start=False, sequence_end=False):
    shape = [1, 16] if batched else [16]
    in0 = InferInput("INPUT0", shape, "INT32")
    in0.set_data_from_numpy(
        (np.arange(16, dtype=np.int32) + seed).reshape(shape))
    in1 = InferInput("INPUT1", shape, "INT32")
    in1.set_data_from_numpy(np.ones(shape, dtype=np.int32))
    return get_inference_request(
        model_name=model, inputs=[in0, in1], model_version="",
        outputs=None, request_id=request_id, sequence_id=sequence_id,
        sequence_start=sequence_start, sequence_end=sequence_end,
        priority=0, timeout=None)


def _span_names(record):
    return [s["name"] for s in record["spans"]]


def _span(record, name):
    for s in record["spans"]:
        if s["name"] == name:
            return s
    return None


# -- settings semantics ---------------------------------------------------


def test_per_model_override_and_revert_on_clear(core):
    baseline = core.trace_setting("", {})
    core.trace_setting("", {"trace_rate": ["7"]})
    try:
        core.trace_setting("simple", {"trace_rate": ["3"]})
        assert core.trace_setting("simple", {})["trace_rate"] == ["3"]
        # Other models keep following the global value.
        assert core.trace_setting("add_sub_fp32", {})["trace_rate"] \
            == ["7"]
        # Clearing the per-model key reverts it to the global value
        # (a copy taken at clear time — the documented semantics).
        core.trace_setting("simple", {"trace_rate": []})
        assert core.trace_setting("simple", {})["trace_rate"] == ["7"]
        # A model never updated is NOT frozen by reads: later global
        # updates flow through to it.
        core.trace_setting("", {"trace_rate": ["9"]})
        assert core.trace_setting("add_sub_fp32", {})["trace_rate"] \
            == ["9"]
    finally:
        core.trace_setting(
            "", {"trace_rate": baseline.get("trace_rate") or ["1000"]})


def test_trace_mode_setting_default_and_roundtrip(core):
    settings = core.trace_setting("", {})
    assert settings.get("trace_mode") == ["compact"]
    core.trace_setting("simple", {"trace_mode": ["chrome"]})
    assert core.trace_setting("simple", {})["trace_mode"] == ["chrome"]
    core.trace_setting("simple", {"trace_mode": []})
    assert core.trace_setting("simple", {})["trace_mode"] == ["compact"]


def test_trace_count_rearm_on_update_http(stack, core, tmp_path):
    """trace_count caps emission; a settings update re-arms the
    counters (Triton semantics) — exercised over the HTTP settings
    endpoint this time (the gRPC path has its own e2e test)."""
    path = tmp_path / "rearm.jsonl"
    with httpclient.InferenceServerClient(stack["http"]) as client:
        client.update_trace_settings("simple", {
            "trace_level": ["TIMESTAMPS"], "trace_rate": "1",
            "trace_count": "2", "log_frequency": "1",
            "trace_file": str(path)})
        _, _, inputs = _http_inputs()
        for _ in range(4):
            client.infer("simple", inputs)
        assert len(_records(path)) == 2
        client.update_trace_settings("simple", {
            "trace_level": ["TIMESTAMPS"], "trace_rate": "1",
            "trace_count": "3", "log_frequency": "1",
            "trace_file": str(path)})
        for _ in range(5):
            client.infer("simple", inputs)
        assert len(_records(path)) == 5  # 2 + re-armed 3
        client.update_trace_settings("simple", {"trace_level": ["OFF"]})


def test_buffered_flush_under_pre_update_settings(core, tmp_path):
    """Records buffered under log_frequency land in the file they were
    recorded FOR when a settings update redirects the sink: the buffer
    is flushed under its pre-update settings."""
    old = tmp_path / "pre.jsonl"
    new = tmp_path / "post.jsonl"
    _enable(core, old, model="simple", freq=100)
    for i in range(3):
        core.infer(_request(seed=i))
    assert not old.exists() or not _records(old)  # still buffered
    _enable(core, new, model="simple", freq=1)
    assert len(_records(old)) == 3  # flushed into the OLD file
    core.infer(_request(seed=99))
    assert len(_records(new)) == 1  # new records go to the new sink
    core.trace_setting("simple", {"trace_level": ["OFF"]})


def test_shutdown_flushes_buffered_records(tmp_path):
    own_core = build_core(["simple"])
    path = tmp_path / "shutdown.jsonl"
    _enable(own_core, path, freq=1000)
    own_core.infer(_request())
    own_core.shutdown()
    records = _records(path)
    assert len(records) == 1
    assert records[0]["model_name"] == "simple"


# -- golden span trees ----------------------------------------------------


def test_direct_path_span_tree_and_legacy_timestamps(core, tmp_path):
    path = tmp_path / "direct.jsonl"
    _enable(core, path, model="simple")
    response = core.infer(_request(seed=5, request_id="direct-1"))
    core.trace_setting("simple", {"trace_level": ["OFF"]})
    (record,) = _records(path)
    names = _span_names(record)
    assert names[0] == "request"
    assert "decode" in names and "device_execute" in names \
        and "encode" in names
    # Legacy five-point timeline rides along, monotonic.
    stamps = [t["ns"] for t in record["timestamps"]]
    assert [t["name"] for t in record["timestamps"]] == [
        "REQUEST_START", "QUEUE_START", "COMPUTE_START", "COMPUTE_END",
        "REQUEST_END"]
    assert stamps == sorted(stamps)
    # The id echoes on the response and stamps the trace record.
    assert response.id == "direct-1"
    assert record["request_id"] == "direct-1"
    # Non-root spans parent to the root.
    root = _span(record, "request")
    for span in record["spans"][1:]:
        if not (span.get("attrs") or {}).get("shared"):
            assert span["parent_span_id"] == root["span_id"]


def test_cache_hit_miss_and_singleflight_follower_span_trees(
        core, tmp_path):
    path = tmp_path / "cache.jsonl"
    _enable(core, path, model="simple_cache")
    core.infer(_request("simple_cache", seed=301, batched=True))
    core.infer(_request("simple_cache", seed=301, batched=True))
    # Single-flight: a barrier burst of identical NEW requests — one
    # leads (miss), the rest coalesce as followers inside the leader's
    # ~1 ms gather window.
    burst = 4
    barrier = threading.Barrier(burst)
    request_proto = _request("simple_cache", seed=302, batched=True)

    def fire():
        barrier.wait()
        core.infer(request_proto)

    pool = [threading.Thread(target=fire) for _ in range(burst)]
    for t in pool:
        t.start()
    for t in pool:
        t.join()
    core.trace_setting("simple_cache", {"trace_level": ["OFF"]})
    records = _records(path)
    outcomes = [
        (_span(r, "cache_lookup") or {}).get("attrs", {}).get("outcome")
        for r in records
    ]
    assert outcomes[0] == "miss"
    assert outcomes[1] == "hit"
    # Miss rides the scheduler: queue + shared batch execution +
    # relay fetch + insert all visible.
    miss = records[0]
    for name in ("decode", "queue", "batch_execute", "relay_fetch",
                 "encode", "cache_insert"):
        assert name in _span_names(miss), name
    assert (_span(miss, "batch_execute")["attrs"] or {}).get("shared")
    # Hit bypasses everything: lookup only, no execution spans.
    hit = records[1]
    assert "batch_execute" not in _span_names(hit)
    assert "queue" not in _span_names(hit)
    burst_outcomes = outcomes[2:]
    assert burst_outcomes.count("miss") == 1
    assert any(o in ("follower", "hit") for o in burst_outcomes)
    for record, outcome in zip(records[2:], burst_outcomes):
        if outcome == "follower":
            wait = _span(record, "cache_wait")
            assert wait is not None
            assert wait["attrs"]["outcome"] == "served"


def test_fused_requests_share_one_batch_execute_span(core, tmp_path):
    """Two distinct concurrent requests fused by the dynamic batcher
    record THE SAME batch-execution span (same span id, requests=2) —
    the trace-level proof of fusion."""
    for attempt in range(4):
        path = tmp_path / ("fused%d.jsonl" % attempt)
        _enable(core, path, model="simple_cache")
        barrier = threading.Barrier(2)
        seeds = (1000 + attempt * 10, 1001 + attempt * 10)

        def fire(seed):
            barrier.wait()
            core.infer(_request("simple_cache", seed=seed, batched=True))

        pool = [threading.Thread(target=fire, args=(s,)) for s in seeds]
        for t in pool:
            t.start()
        for t in pool:
            t.join()
        core.trace_setting("simple_cache", {"trace_level": ["OFF"]})
        records = _records(path)
        spans = [_span(r, "batch_execute") for r in records]
        if all(s is not None for s in spans) \
                and spans[0]["span_id"] == spans[1]["span_id"]:
            assert spans[0]["attrs"]["requests"] == 2
            assert spans[0]["attrs"]["shared"] is True
            return
    pytest.fail("requests never fused into one batch-execution span "
                "in 4 attempts")


def test_sequence_step_span_tree(core, tmp_path):
    path = tmp_path / "sequence.jsonl"
    _enable(core, path, model="dyna_sequence")
    in0 = InferInput("INPUT", [1, 1], "INT32")
    in0.set_data_from_numpy(np.array([[7]], dtype=np.int32))
    start = get_inference_request(
        model_name="dyna_sequence", inputs=[in0], model_version="",
        outputs=None, request_id="seq-step", sequence_id=4242,
        sequence_start=True, sequence_end=False, priority=0,
        timeout=None)
    end = get_inference_request(
        model_name="dyna_sequence", inputs=[in0], model_version="",
        outputs=None, request_id="", sequence_id=4242,
        sequence_start=False, sequence_end=True, priority=0,
        timeout=None)
    core.infer(start)
    core.infer(end)
    core.trace_setting("dyna_sequence", {"trace_level": ["OFF"]})
    records = _records(path)
    assert len(records) == 2
    first = records[0]
    wait = _span(first, "sequence_slot_wait")
    assert wait is not None
    assert wait["attrs"]["corrid"] == "4242"
    assert wait["attrs"]["start"] is True
    # Oldest strategy: the step dispatched through the dynamic batcher.
    assert "queue" in _span_names(first)
    assert "batch_execute" in _span_names(first)
    assert first["request_id"] == "seq-step"


def test_decoupled_stream_per_response_spans(core, tmp_path):
    path = tmp_path / "stream.jsonl"
    _enable(core, path, model="repeat_int32")
    tensor = InferInput("IN", [3], "INT32")
    tensor.set_data_from_numpy(np.array([4, 5, 6], dtype=np.int32))
    request = get_inference_request(
        model_name="repeat_int32", inputs=[tensor], model_version="",
        outputs=None, request_id="", sequence_id=0,
        sequence_start=False, sequence_end=False, priority=0,
        timeout=None)
    responses = list(core.stream_infer(request))
    core.trace_setting("repeat_int32", {"trace_level": ["OFF"]})
    data = [r for r in responses if r.infer_response.outputs]
    assert len(data) == 3
    (record,) = _records(path)
    stream_spans = [s for s in record["spans"]
                    if s["name"] == "stream_response"]
    assert [s["attrs"]["index"] for s in stream_spans] == [0, 1, 2]
    assert "decode" in _span_names(record)


def test_chrome_trace_mode_emits_perfetto_events(core, tmp_path):
    path = tmp_path / "chrome.json"
    _enable(core, path, model="simple", mode="chrome")
    core.infer(_request(seed=77))
    core.trace_setting("simple", {"trace_level": ["OFF"]})
    text = path.read_text()
    assert text.startswith("[")
    # The chrome format allows the missing close bracket; complete it
    # to parse here.
    events = json.loads(text.rstrip().rstrip(",") + "]")
    phases = {e.get("ph") for e in events}
    assert "X" in phases and "M" in phases
    names = [e["name"] for e in events if e.get("ph") == "X"]
    assert "request" in names and "device_execute" in names
    request_event = next(e for e in events if e["name"] == "request")
    assert request_event["args"]["trace_id"]
    assert request_event["dur"] > 0


# -- trace-context propagation (all four clients) -------------------------


def _http_inputs():
    in0 = np.arange(16, dtype=np.int32)
    in1 = np.ones(16, dtype=np.int32)
    inputs = [httpclient.InferInput("INPUT0", [16], "INT32"),
              httpclient.InferInput("INPUT1", [16], "INT32")]
    inputs[0].set_data_from_numpy(in0)
    inputs[1].set_data_from_numpy(in1)
    return in0, in1, inputs


def _grpc_inputs():
    in0 = np.arange(16, dtype=np.int32)
    in1 = np.ones(16, dtype=np.int32)
    inputs = [grpcclient.InferInput("INPUT0", [16], "INT32"),
              grpcclient.InferInput("INPUT1", [16], "INT32")]
    inputs[0].set_data_from_numpy(in0)
    inputs[1].set_data_from_numpy(in1)
    return in0, in1, inputs


def test_propagation_http_sync(stack, core, tmp_path):
    path = tmp_path / "prop_http.jsonl"
    _enable(core, path, model="simple")
    tracer = ClientTracer()
    with httpclient.InferenceServerClient(stack["http"],
                                          tracer=tracer) as client:
        _, _, inputs = _http_inputs()
        client.infer("simple", inputs, request_id="prop-http")
    core.trace_setting("simple", {"trace_level": ["OFF"]})
    (client_record,) = tracer.records()
    (server_record,) = _records(path)
    # Same trace id across the wire; the client span parents the
    # server root.
    assert server_record["trace_id"] == client_record["trace_id"]
    assert server_record["parent_span_id"] == client_record["span_id"]
    assert client_record["attrs"]["transport"] == "http"
    assert server_record["request_id"] == "prop-http"


def test_propagation_grpc_sync_and_caller_supplied(stack, core,
                                                   tmp_path):
    path = tmp_path / "prop_grpc.jsonl"
    _enable(core, path, model="simple")
    tracer = ClientTracer()
    with grpcclient.InferenceServerClient(stack["grpc"],
                                          tracer=tracer) as client:
        _, _, inputs = _grpc_inputs()
        client.infer("simple", inputs)
        # Caller-supplied traceparent wins over the tracer-minted one.
        supplied = format_traceparent("ab" * 16, "cd" * 8)
        client.infer("simple", inputs,
                     headers={"traceparent": supplied})
    core.trace_setting("simple", {"trace_level": ["OFF"]})
    records = _records(path)
    client_records = tracer.records()
    assert records[0]["trace_id"] == client_records[0]["trace_id"]
    assert records[0]["parent_span_id"] == client_records[0]["span_id"]
    assert records[1]["trace_id"] == "ab" * 16
    assert records[1]["parent_span_id"] == "cd" * 8
    # The tracer adopted the supplied trace id for its own span too.
    assert client_records[1]["trace_id"] == "ab" * 16


def test_propagation_aio_clients(stack, core, tmp_path):
    import client_tpu.grpc.aio as grpcaio
    import client_tpu.http.aio as httpaio

    path = tmp_path / "prop_aio.jsonl"
    _enable(core, path, model="simple")
    grpc_tracer = ClientTracer()
    http_tracer = ClientTracer()

    async def run():
        async with grpcaio.InferenceServerClient(
                stack["grpc"], tracer=grpc_tracer) as client:
            _, _, inputs = _grpc_inputs()
            await client.infer("simple", inputs)
        async with httpaio.InferenceServerClient(
                stack["http"], tracer=http_tracer) as client:
            _, _, inputs = _http_inputs()
            await client.infer("simple", inputs)

    asyncio.run(run())
    core.trace_setting("simple", {"trace_level": ["OFF"]})
    records = _records(path)
    assert len(records) == 2
    (grpc_span,) = grpc_tracer.records()
    (http_span,) = http_tracer.records()
    assert records[0]["trace_id"] == grpc_span["trace_id"]
    assert records[0]["parent_span_id"] == grpc_span["span_id"]
    assert records[1]["trace_id"] == http_span["trace_id"]
    assert records[1]["parent_span_id"] == http_span["span_id"]


def test_malformed_traceparent_is_ignored(core, tmp_path):
    path = tmp_path / "malformed.jsonl"
    _enable(core, path, model="simple")
    core.infer(_request(), trace_context="zz-not-a-traceparent")
    core.trace_setting("simple", {"trace_level": ["OFF"]})
    (record,) = _records(path)
    assert record["parent_span_id"] is None
    assert len(record["trace_id"]) == 32
    assert parse_traceparent("zz-not-a-traceparent") is None
    assert parse_traceparent(
        format_traceparent("ab" * 16, "cd" * 8)) == ("ab" * 16, "cd" * 8)


# -- request-id correlation -----------------------------------------------


def test_request_id_minted_and_echoed_both_transports(stack, core):
    with httpclient.InferenceServerClient(stack["http"]) as client:
        _, _, inputs = _http_inputs()
        result = client.infer("simple", inputs)
        assert result.get_response().get("id")
    with grpcclient.InferenceServerClient(stack["grpc"]) as client:
        _, _, inputs = _grpc_inputs()
        response = client.infer("simple", inputs)
        assert response.get_response().id
        # Caller-supplied ids are preserved verbatim.
        response = client.infer("simple", inputs, request_id="mine-1")
        assert response.get_response().id == "mine-1"


def test_error_log_carries_request_id(core, caplog):
    bad = _request(seed=0)
    bad.id = "failing-req"
    bad.inputs[0].name = "NO_SUCH_INPUT"
    with caplog.at_level(logging.DEBUG, logger="client_tpu.server"):
        with pytest.raises(InferenceServerException):
            core.infer(bad)
    assert any("failing-req" in message
               for message in caplog.messages)


def test_tracing_off_has_no_file_side_effects(core, tmp_path):
    path = tmp_path / "off.jsonl"
    # Level OFF: nothing written even with a file configured.
    core.trace_setting("simple", {
        "trace_level": ["OFF"], "trace_file": [str(path)],
        "trace_rate": ["1"]})
    core.infer(_request())
    assert not path.exists()
    # Level set but NO file: tracing stays off (no implicit sink).
    core.trace_setting("simple", {
        "trace_level": ["TIMESTAMPS"], "trace_file": [""]})
    core.infer(_request())
    core.trace_setting("simple", {"trace_level": ["OFF"]})
    assert not path.exists()


# -- metrics lint (satellite) ---------------------------------------------


def test_metrics_lint_accepts_live_exposition(core):
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    from metrics_lint import check_monotonic, lint_exposition

    core.infer(_request(seed=11))
    errors, types, before = lint_exposition(core.metrics_text())
    assert errors == []
    core.infer(_request(seed=12))
    errors, types, after = lint_exposition(core.metrics_text())
    assert errors == []
    assert check_monotonic(types, before, after) == []
    assert types.get("nv_inference_count") == "counter"


def test_metrics_lint_flags_violations():
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    from metrics_lint import check_monotonic, lint_exposition

    bad = "\n".join([
        '# HELP a_total ok',
        '# TYPE a_total counter',
        'a_total{m="x"} 5',
        'a_total{m="x"} 6',          # duplicate series
        'orphan_metric 1',           # no HELP/TYPE
        '# HELP late ok',
        'late 2',
        '# TYPE late gauge',         # TYPE after sample
        '# HELP b_total ok',
        '# TYPE b_total gauge',      # _total typed gauge
        'b_total 1',
    ])
    errors, types, series = lint_exposition(bad)
    text = "\n".join(errors)
    assert "duplicate series" in text
    assert "orphan_metric" in text
    assert "TYPE appears after" in text
    assert "_total but is typed" in text
    # Monotonicity: a decreasing counter is flagged.
    decreased = check_monotonic(
        {"a_total": "counter"}, {("a_total", 'm="x"'): 5.0},
        {("a_total", 'm="x"'): 4.0})
    assert decreased and "decreased" in decreased[0]
