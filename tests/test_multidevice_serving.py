"""Serving a sharded model through the server + TPU-shm (arena) path
on a multi-device mesh (the conftest provides a virtual 8-device CPU
mesh). Round-2 gap: the LLM accepted a mesh but nothing ever served a
tp-sharded model in serving position."""

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
from client_tpu.utils import serialize_byte_tensor


@pytest.fixture(scope="module")
def sharded_server():
    import jax

    from client_tpu.models.llm import LlmConfig, LlmModel
    from client_tpu.parallel import create_mesh
    from client_tpu.server.app import build_core, start_grpc_server

    devices = jax.devices()
    assert len(devices) >= 8, "conftest should provide 8 CPU devices"
    # tp=2 divides n_kv_heads=2 (the tightest sharded dim)
    mesh = create_mesh((("dp", 2), ("sp", 1), ("tp", 2)),
                       devices=devices[:4])
    cfg = LlmConfig(vocab=264, d_model=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=128, max_seq=64)
    core = build_core([])
    model = LlmModel(name="llm_sharded", cfg=cfg, mesh=mesh)
    core.repository.add_model(model)
    handle = start_grpc_server(core=core)
    yield {"core": core, "address": handle.address, "mesh": mesh,
           "model": model}
    handle.stop()


def test_params_actually_sharded(sharded_server):
    """The served model's parameters live on all mesh devices."""
    import jax

    params = sharded_server["model"]._params
    leaves = jax.tree.leaves(params)
    sharded = [
        leaf for leaf in leaves
        if hasattr(leaf, "sharding") and len(leaf.sharding.device_set) > 1
    ]
    assert sharded, "no parameter is sharded across the mesh"


def test_sharded_model_serves_over_grpc(sharded_server):
    with grpcclient.InferenceServerClient(
            sharded_server["address"]) as client:
        inputs = [
            grpcclient.InferInput("text_input", [1], "BYTES"),
            grpcclient.InferInput("max_tokens", [1], "INT32"),
        ]
        inputs[0].set_data_from_numpy(
            np.array([b"hello"], dtype=np.object_))
        inputs[1].set_data_from_numpy(np.array([4], dtype=np.int32))
        responses = []
        client.start_stream(
            callback=lambda result, error: responses.append((result, error)))
        client.async_stream_infer("llm_sharded", inputs)
        import time

        deadline = time.time() + 120
        while time.time() < deadline:
            final = [
                r for r, e in responses
                if r is not None and r.get_response().parameters.get(
                    "triton_final_response") is not None
            ]
            if final or any(e is not None for _, e in responses):
                break
            time.sleep(0.2)
        client.stop_stream()
        errors = [e for _, e in responses if e is not None]
        assert not errors, errors[0]
        texts = [r.as_numpy("text_output") for r, _ in responses
                 if r is not None and r.as_numpy("text_output") is not None]
        assert texts, "no streamed tokens from the sharded model"


def test_sharded_model_serves_through_arena(sharded_server):
    """TPU-shm path with a sharded model: input rides an arena region,
    output lands back in one by reference."""
    core = sharded_server["core"]
    arena = core.memory.arena
    if arena is None:
        pytest.skip("no arena on this platform")
    payload = serialize_byte_tensor(
        np.array([b"hi"], dtype=np.object_)).tobytes()
    in_handle = arena.create_region(max(len(payload), 64), 0)
    from client_tpu.protocol import inference_pb2 as pb

    core.memory.register_tpu("llm_in", in_handle, 0, max(len(payload), 64))
    out_handle = arena.create_region(4096, 0)
    core.memory.register_tpu("llm_out", out_handle, 0, 4096)
    try:
        # place the serialized BYTES tensor into the input region
        region = core.memory._get("llm_in")
        arena.write(region.region_id, 0, payload, "BYTES", [1])

        request = pb.ModelInferRequest(model_name="llm_sharded")
        tensor = request.inputs.add()
        tensor.name = "text_input"
        tensor.datatype = "BYTES"
        tensor.shape.extend([1])
        tensor.parameters["shared_memory_region"].string_param = "llm_in"
        tensor.parameters["shared_memory_byte_size"].int64_param = len(
            payload)
        mt = request.inputs.add()
        mt.name = "max_tokens"
        mt.datatype = "INT32"
        mt.shape.extend([1])
        request.raw_input_contents.append(
            np.array([2], dtype=np.int32).tobytes())
        out = request.outputs.add()
        out.name = "text_output"
        out.parameters["shared_memory_region"].string_param = "llm_out"
        out.parameters["shared_memory_byte_size"].int64_param = 4096

        responses = list(core.stream_infer(request))
        assert responses, "no responses from sharded stream via arena"
        # Outputs were placed into the region BY REFERENCE: the region
        # must hold real segments (arena.read zero-fills an untouched
        # region, so a bytes-truthiness check would be vacuous).
        out_region = core.memory._get("llm_out")
        segments = arena._get(out_region.region_id).segments
        assert segments, "no output segment was stored in the region"
        data = arena.read(out_region.region_id, 0, 0)
        assert any(data), "output region holds only zeros"
    finally:
        core.memory.unregister_tpu(None)
