"""Sanity checks for the Java client sources (this image ships no JDK;
when `javac` is present the whole tree must compile — parity: the
reference's maven-built src/java)."""

import pathlib
import shutil
import subprocess

import pytest

JAVA_ROOT = (
    pathlib.Path(__file__).resolve().parent.parent / "java" / "src" / "main"
    / "java"
)


def _sources():
    return sorted(JAVA_ROOT.rglob("*.java"))


def test_sources_exist():
    names = {p.name for p in _sources()}
    assert {
        "InferenceServerClient.java", "InferInput.java",
        "InferRequestedOutput.java", "InferResult.java", "DataType.java",
        "InferenceException.java", "Json.java", "SimpleInferClient.java",
    } <= names


@pytest.mark.parametrize("path", _sources(), ids=lambda p: p.name)
def test_source_well_formed(path):
    text = path.read_text()
    # Balanced braces/parens outside of strings & comments.
    depth_brace = depth_paren = 0
    in_string = in_char = in_line_comment = in_block_comment = False
    prev = ""
    for ch in text:
        if in_line_comment:
            if ch == "\n":
                in_line_comment = False
        elif in_block_comment:
            if prev == "*" and ch == "/":
                in_block_comment = False
        elif in_string:
            if ch == '"' and prev != "\\":
                in_string = False
        elif in_char:
            if ch == "'" and prev != "\\":
                in_char = False
        elif prev == "/" and ch == "/":
            in_line_comment = True
        elif prev == "/" and ch == "*":
            in_block_comment = True
        elif ch == '"':
            in_string = True
        elif ch == "'":
            in_char = True
        elif ch == "{":
            depth_brace += 1
        elif ch == "}":
            depth_brace -= 1
        elif ch == "(":
            depth_paren += 1
        elif ch == ")":
            depth_paren -= 1
        prev = "" if (prev == "\\" and ch == "\\") else ch
    assert depth_brace == 0, "unbalanced braces in %s" % path.name
    assert depth_paren == 0, "unbalanced parens in %s" % path.name
    assert "package tpuclient" in text


def test_client_api_surface():
    text = (JAVA_ROOT / "tpuclient" / "InferenceServerClient.java").read_text()
    for method in (
        "isServerLive", "isServerReady", "isModelReady", "getServerMetadata",
        "getModelMetadata", "getModelConfig", "getInferenceStatistics",
        "loadModel", "unloadModel", "registerSystemSharedMemory",
        "registerTpuSharedMemory", "infer", "asyncInfer",
        # robustness surface (parity: reference :245,368)
        "setRetryCnt", "AbstractEndpoint",
    ):
        assert method in text, "missing method %s" % method


def test_retry_and_endpoint_abstraction():
    """Bounded transport retry + endpoint strategy classes (parity:
    reference InferenceServerClient.java:245,293 and endpoint/)."""
    client = (JAVA_ROOT / "tpuclient"
              / "InferenceServerClient.java").read_text()
    # retry loop: bounded by retryCnt, rebuilds the request per attempt
    assert "retryCnt" in client
    assert "attempt >= retryCnt" in client
    assert "catch (IOException" in client
    # constructor overloads accept an endpoint strategy
    assert "InferenceServerClient(AbstractEndpoint endpoint" in client
    names = {p.name for p in _sources()}
    assert {"AbstractEndpoint.java", "FixedEndpoint.java",
            "RoundRobinEndpoint.java"} <= names
    fixed = (JAVA_ROOT / "tpuclient" / "endpoint"
             / "FixedEndpoint.java").read_text()
    assert "extends AbstractEndpoint" in fixed
    rr = (JAVA_ROOT / "tpuclient" / "endpoint"
          / "RoundRobinEndpoint.java").read_text()
    assert "extends AbstractEndpoint" in rr
    assert "getAndIncrement" in rr  # actually rotates


def test_compiles_if_jdk_available(tmp_path):
    javac = shutil.which("javac")
    if javac is None:
        pytest.skip("no JDK in this image — install openjdk (e.g. apt "
                    "install openjdk-17-jdk-headless) to compile the "
                    "Java client")
    proc = subprocess.run(
        [javac, "-d", str(tmp_path)] + [str(p) for p in _sources()],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr


GOLDEN = pathlib.Path(__file__).resolve().parent / "golden" / \
    "simple_infer_request.golden"


def _canonical_request():
    """The canonical 'simple' request both clients must serialize
    identically (java/examples/WireFormatCheck.java builds the same)."""
    import numpy as np

    from client_tpu.http import (
        InferenceServerClient,
        InferInput,
        InferRequestedOutput,
    )

    i0 = InferInput("INPUT0", [16], "INT32")
    i0.set_data_from_numpy(np.arange(16, dtype=np.int32))
    i1 = InferInput("INPUT1", [16], "INT32")
    i1.set_data_from_numpy(np.ones(16, dtype=np.int32))
    o0 = InferRequestedOutput("OUTPUT0", binary_data=True)
    o1 = InferRequestedOutput("OUTPUT1", binary_data=True)
    return InferenceServerClient.generate_request_body(
        [i0, i1], outputs=[o0, o1])


def _parse_golden(text):
    import base64
    import json

    lines = text.strip().splitlines()
    header_len = int(lines[0])
    body = base64.b64decode(lines[1])
    return json.loads(body[:header_len]), body[header_len:]


def test_python_wire_format_matches_golden():
    """Guards the Python client's binary protocol against drift."""
    import base64
    import json

    body, header_len = _canonical_request()
    golden_header, golden_payload = _parse_golden(GOLDEN.read_text())
    assert json.loads(body[:header_len]) == golden_header
    assert body[header_len:] == golden_payload


def test_java_wire_format_matches_golden(tmp_path):
    """Compiles the Java client and asserts its binary request bytes
    equal the Python client's (semantically-equal JSON header,
    byte-equal tensor payload). Skipped without a JDK."""
    import json
    import subprocess as sp

    javac = shutil.which("javac")
    java = shutil.which("java")
    if not (javac and java):
        pytest.skip("no JDK on this image — install openjdk (e.g. apt "
                    "install openjdk-17-jdk-headless) to run the Java "
                    "wire-format conformance check")
    classes = tmp_path / "classes"
    classes.mkdir()
    sources = [str(p) for p in _sources()]
    compile_proc = sp.run(
        [javac, "-d", str(classes)] + sources,
        capture_output=True, text=True, timeout=300,
    )
    assert compile_proc.returncode == 0, compile_proc.stderr
    run_proc = sp.run(
        [java, "-cp", str(classes), "tpuclient.examples.WireFormatCheck"],
        capture_output=True, text=True, timeout=120,
    )
    assert run_proc.returncode == 0, run_proc.stderr
    golden_header, golden_payload = _parse_golden(GOLDEN.read_text())
    java_header, java_payload = _parse_golden(run_proc.stdout)
    assert java_header == golden_header
    assert java_payload == golden_payload
