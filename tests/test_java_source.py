"""Sanity checks for the Java client sources (this image ships no JDK;
when `javac` is present the whole tree must compile — parity: the
reference's maven-built src/java)."""

import pathlib
import shutil
import subprocess

import pytest

JAVA_ROOT = (
    pathlib.Path(__file__).resolve().parent.parent / "java" / "src" / "main"
    / "java"
)


def _sources():
    return sorted(JAVA_ROOT.rglob("*.java"))


def test_sources_exist():
    names = {p.name for p in _sources()}
    assert {
        "InferenceServerClient.java", "InferInput.java",
        "InferRequestedOutput.java", "InferResult.java", "DataType.java",
        "InferenceException.java", "Json.java", "SimpleInferClient.java",
    } <= names


@pytest.mark.parametrize("path", _sources(), ids=lambda p: p.name)
def test_source_well_formed(path):
    text = path.read_text()
    # Balanced braces/parens outside of strings & comments.
    depth_brace = depth_paren = 0
    in_string = in_char = in_line_comment = in_block_comment = False
    prev = ""
    for ch in text:
        if in_line_comment:
            if ch == "\n":
                in_line_comment = False
        elif in_block_comment:
            if prev == "*" and ch == "/":
                in_block_comment = False
        elif in_string:
            if ch == '"' and prev != "\\":
                in_string = False
        elif in_char:
            if ch == "'" and prev != "\\":
                in_char = False
        elif prev == "/" and ch == "/":
            in_line_comment = True
        elif prev == "/" and ch == "*":
            in_block_comment = True
        elif ch == '"':
            in_string = True
        elif ch == "'":
            in_char = True
        elif ch == "{":
            depth_brace += 1
        elif ch == "}":
            depth_brace -= 1
        elif ch == "(":
            depth_paren += 1
        elif ch == ")":
            depth_paren -= 1
        prev = "" if (prev == "\\" and ch == "\\") else ch
    assert depth_brace == 0, "unbalanced braces in %s" % path.name
    assert depth_paren == 0, "unbalanced parens in %s" % path.name
    assert "package tpuclient" in text


def test_client_api_surface():
    text = (JAVA_ROOT / "tpuclient" / "InferenceServerClient.java").read_text()
    for method in (
        "isServerLive", "isServerReady", "isModelReady", "getServerMetadata",
        "getModelMetadata", "getModelConfig", "getInferenceStatistics",
        "loadModel", "unloadModel", "registerSystemSharedMemory",
        "registerTpuSharedMemory", "infer", "asyncInfer",
    ):
        assert method in text, "missing method %s" % method


def test_compiles_if_jdk_available(tmp_path):
    javac = shutil.which("javac")
    if javac is None:
        pytest.skip("no JDK in this image")
    proc = subprocess.run(
        [javac, "-d", str(tmp_path)] + [str(p) for p in _sources()],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
