"""Source-level checks for the JNI api-bindings (java/api-bindings):
every Java native method must have a matching JNI export with the
mangled name, and the shim must stay on the bytes-in/bytes-out
contract. Compile/run coverage is JDK-gated (this image has none), the
same tiering as tests/test_java_source.py."""

import pathlib
import re
import shutil
import subprocess

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
BINDINGS = REPO / "java" / "api-bindings"
JAVA_SRC = (BINDINGS / "src" / "main" / "java" / "tpuclient" / "bindings"
            / "NativeClient.java")
JNI_SRC = BINDINGS / "jni" / "tpuclient_jni.cc"


def test_native_methods_have_jni_exports():
    java = JAVA_SRC.read_text()
    jni = JNI_SRC.read_text()
    natives = re.findall(
        r"private static native \S+ (\w+)\(", java)
    assert sorted(natives) == ["create", "destroy", "infer", "isServerLive"]
    for name in natives:
        symbol = "Java_tpuclient_bindings_NativeClient_" + name
        assert symbol in jni, "missing JNI export %s" % symbol


def test_jni_shim_is_bytes_level():
    """The shim must not re-implement tensor marshalling: it forwards
    serialized protos over the native channel's UnaryCall."""
    jni = JNI_SRC.read_text()
    assert "/inference.GRPCInferenceService/ModelInfer" in jni
    assert "UnaryCall" in jni
    assert "InferInput" not in jni  # no typed marshalling in the shim


def test_cmake_option_wires_the_target():
    cmake = (REPO / "native" / "CMakeLists.txt").read_text()
    assert "TPUCLIENT_JNI" in cmake
    assert "tpuclient_jni.cc" in cmake


def test_compile_when_jdk_present():
    if shutil.which("javac") is None:
        pytest.skip("no JDK in this image — install openjdk (e.g. apt "
                    "install openjdk-17-jdk-headless) to enable "
                    "JNI-shim compilation")
    proc = subprocess.run(
        ["javac", "-d", "/tmp/jni_bindings_classes", str(JAVA_SRC)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
