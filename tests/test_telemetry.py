"""Always-on latency histograms + streaming-token telemetry (PR 10):
unit coverage for the histogram accumulators and quantile estimation,
exposition lint for the new families, e2e TTFT/ITL population over
both transports, bucket-quantile fidelity against trace-derived
latencies on a seeded-latency chaos model, and the exemplar ->
trace-id join."""

import json
import os
import sys
import threading

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
from client_tpu._infer_common import InferInput
from client_tpu.grpc._utils import get_inference_request
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.http_server import start_http_server_thread
from client_tpu.server.telemetry import (
    DEFAULT_BOUNDS_US,
    INF,
    LatencyHistogram,
    ServerTelemetry,
    bucket_width_us,
    estimate_quantile,
    format_le,
)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
from metrics_lint import check_monotonic, lint_exposition  # noqa: E402


# -- histogram unit -------------------------------------------------------


def test_histogram_observe_and_cumulative_snapshot():
    hist = LatencyHistogram()
    hist.observe(3.0)
    hist.observe(30.0)
    hist.observe(1e9)  # beyond the ladder -> +Inf bucket
    snap = hist.snapshot()
    assert snap["count"] == 3
    assert snap["sum"] == pytest.approx(3.0 + 30.0 + 1e9)
    cumulative = dict(snap["buckets"])
    assert cumulative[5] == 1
    assert cumulative[50] == 2
    assert cumulative[10_000_000] == 2
    assert cumulative[INF] == 3
    # the ladder ends at +Inf and is cumulative-non-decreasing
    bounds = [b for b, _ in snap["buckets"]]
    assert bounds[-1] == INF
    counts = [c for _, c in snap["buckets"]]
    assert counts == sorted(counts)


def test_histogram_exemplar_only_for_traced_observations():
    hist = LatencyHistogram()
    hist.observe(10.0)
    assert hist.snapshot()["exemplars"] == {}
    hist.observe(10.0, trace_id="abc123")
    exemplars = hist.snapshot()["exemplars"]
    assert len(exemplars) == 1
    (bound, (trace_id, value, stamp)), = exemplars.items()
    assert trace_id == "abc123"
    assert value == 10.0
    assert bound in DEFAULT_BOUNDS_US


def test_negative_observation_clamps_to_zero():
    hist = LatencyHistogram()
    hist.observe(-5.0)
    snap = hist.snapshot()
    assert snap["count"] == 1
    assert snap["sum"] == 0.0
    assert snap["buckets"][0][1] == 1  # lands in the first bucket


def test_estimate_quantile_linear_interpolation():
    buckets = [(100.0, 50.0), (200.0, 100.0), (INF, 100.0)]
    assert estimate_quantile(buckets, 0.50) == pytest.approx(100.0)
    assert estimate_quantile(buckets, 0.25) == pytest.approx(50.0)
    assert estimate_quantile(buckets, 0.75) == pytest.approx(150.0)
    assert estimate_quantile(buckets, 0.99) == pytest.approx(198.0)


def test_estimate_quantile_edge_cases():
    assert estimate_quantile([], 0.5) == 0.0
    assert estimate_quantile([(100.0, 0.0), (INF, 0.0)], 0.5) == 0.0
    # All mass past the ladder: clamp to the highest finite bound.
    assert estimate_quantile([(100.0, 0.0), (INF, 10.0)], 0.99) == 100.0


def test_bucket_width_and_le_formatting():
    assert bucket_width_us(30.0) == 30.0   # (20, 50]
    assert bucket_width_us(1.0) == 1.0     # (0, 1]
    assert bucket_width_us(1e12) == INF    # beyond the ladder
    assert format_le(100.0) == "100"
    assert format_le(INF) == "+Inf"


# -- registry + exposition ------------------------------------------------


def _lint(text):
    return lint_exposition(text)


def test_registry_render_is_lint_clean_and_typed():
    registry = ServerTelemetry(enabled=True)
    registry.observe_request("m", 120.0, "tid123")
    registry.observe_stage("m", "decode", 5.0)
    registry.observe_stage("m", "batch_execute", 80.0, "tid456")
    registry.observe_stream_first("m", 50.0)
    registry.observe_stream_gap("m", 10.0)
    registry.observe_tenant("t1", 99.0)
    text = "\n".join(registry.render()) + "\n"
    errors, types, series = _lint(text)
    assert errors == []
    for family in ("tpu_request_duration_us", "tpu_stage_duration_us",
                   "tpu_stream_first_response_us",
                   "tpu_stream_inter_response_us",
                   "tpu_tenant_request_duration_us"):
        assert types.get(family) == "histogram", family
    assert types.get("tpu_stream_responses_total") == "counter"
    # The traced observations carry exemplars; untraced ones do not.
    assert '# {trace_id="tid123"}' in text
    assert '# {trace_id="tid456"}' in text


def test_disabled_registry_records_nothing():
    registry = ServerTelemetry(enabled=False)
    registry.observe_request("m", 120.0)
    registry.observe_stream_first("m", 50.0)
    registry.observe_tenant("t", 10.0)
    assert registry.render() == []


def test_tenant_cardinality_folds_into_overflow(monkeypatch):
    monkeypatch.setattr(ServerTelemetry, "MAX_TENANTS", 2)
    registry = ServerTelemetry(enabled=True)
    for i in range(5):
        registry.observe_tenant("tenant-%d" % i, 10.0)
    text = "\n".join(registry.render())
    counts = [line for line in text.splitlines()
              if line.startswith("tpu_tenant_request_duration_us_count")]
    assert len(counts) == 3  # two real tenants + the overflow row
    assert 'tenant="overflow"' in text


# -- lint histogram validation --------------------------------------------


_GOOD_HIST = """\
# HELP tpu_request_duration_us x
# TYPE tpu_request_duration_us histogram
tpu_request_duration_us_bucket{model="m",le="100"} 5 # {trace_id="ab"} 42.0 1690000000.000
tpu_request_duration_us_bucket{model="m",le="+Inf"} 7
tpu_request_duration_us_sum{model="m"} 900.0
tpu_request_duration_us_count{model="m"} 7
"""


def test_lint_accepts_histogram_with_exemplar():
    errors, types, series = _lint(_GOOD_HIST)
    assert errors == []
    # histogram children are typed counter for cross-scrape checks
    assert types["tpu_request_duration_us_bucket"] == "counter"


def test_lint_catches_count_mismatch():
    bad = _GOOD_HIST.replace(
        'tpu_request_duration_us_count{model="m"} 7',
        'tpu_request_duration_us_count{model="m"} 9')
    errors, _, _ = _lint(bad)
    assert any("_count" in e and "+Inf" in e for e in errors)


def test_lint_catches_missing_inf_bucket():
    bad = "\n".join(line for line in _GOOD_HIST.splitlines()
                    if 'le="+Inf"' not in line) + "\n"
    errors, _, _ = _lint(bad)
    assert any("does not end" in e for e in errors)


def test_lint_catches_decreasing_bucket_ladder():
    bad = _GOOD_HIST.replace(
        'tpu_request_duration_us_bucket{model="m",le="+Inf"} 7',
        'tpu_request_duration_us_bucket{model="m",le="+Inf"} 3')
    errors, _, _ = _lint(bad)
    assert any("decreases" in e or "_count" in e for e in errors)


def test_lint_catches_missing_sum():
    bad = "\n".join(line for line in _GOOD_HIST.splitlines()
                    if "_sum" not in line) + "\n"
    errors, _, _ = _lint(bad)
    assert any("missing _sum" in e for e in errors)


def test_lint_hostile_label_value_is_not_an_exemplar():
    """An escaped label VALUE may legally contain '# {...}' (tenant
    identity is client-supplied); the exemplar splitter must not
    mangle such a sample."""
    hostile = (
        "# HELP tpu_tenant_success_total x\n"
        "# TYPE tpu_tenant_success_total counter\n"
        'tpu_tenant_success_total{tenant="a # {b} c"} 5\n')
    errors, _, series = _lint(hostile)
    assert errors == []
    assert ("tpu_tenant_success_total",
            'tenant="a # {b} c"') in series


def test_lint_rejects_malformed_exemplar():
    bad = _GOOD_HIST.replace('# {trace_id="ab"} 42.0 1690000000.000',
                             '# {trace_id=ab} 42.0')
    errors, _, _ = _lint(bad)
    assert any("exemplar" in e for e in errors)


def test_histogram_buckets_monotonic_across_scrapes():
    after = _GOOD_HIST.replace(
        'tpu_request_duration_us_bucket{model="m",le="100"} 5 ',
        'tpu_request_duration_us_bucket{model="m",le="100"} 3 ')
    errors_a, types, before_series = _lint(_GOOD_HIST)
    errors_b, types_b, after_series = _lint(after)
    violations = check_monotonic(types_b, before_series, after_series)
    assert any("tpu_request_duration_us_bucket" in v
               for v in violations)


# -- metrics_manager scrape + quantiles -----------------------------------


_SCRAPE_BEFORE = """\
# TYPE tpu_request_duration_us histogram
tpu_request_duration_us_bucket{model="simple",le="100"} 10
tpu_request_duration_us_bucket{model="simple",le="200"} 10
tpu_request_duration_us_bucket{model="simple",le="+Inf"} 10
tpu_request_duration_us_sum{model="simple"} 500.0
tpu_request_duration_us_count{model="simple"} 10
"""

_SCRAPE_AFTER = """\
# TYPE tpu_request_duration_us histogram
tpu_request_duration_us_bucket{model="simple",le="100"} 60
tpu_request_duration_us_bucket{model="simple",le="200"} 110
tpu_request_duration_us_bucket{model="simple",le="+Inf"} 110
tpu_request_duration_us_sum{model="simple"} 13000.0
tpu_request_duration_us_count{model="simple"} 110
# TYPE tpu_stream_first_response_us histogram
tpu_stream_first_response_us_bucket{model="llm",le="1000"} 4
tpu_stream_first_response_us_bucket{model="llm",le="+Inf"} 4
tpu_stream_first_response_us_sum{model="llm"} 2000.0
tpu_stream_first_response_us_count{model="llm"} 4
# TYPE tpu_stage_duration_us histogram
tpu_stage_duration_us_bucket{model="simple",stage="queue",le="50"} 8
tpu_stage_duration_us_bucket{model="simple",stage="queue",le="+Inf"} 8
tpu_stage_duration_us_sum{model="simple",stage="queue"} 100.0
tpu_stage_duration_us_count{model="simple",stage="queue"} 8
"""


def test_scrape_parses_histogram_children():
    from client_tpu.perf.metrics_manager import parse_prometheus

    snap = parse_prometheus(_SCRAPE_AFTER)
    buckets = snap.histograms["request_duration_us"]["simple"]
    assert buckets[100.0] == 60
    assert buckets[float("inf")] == 110
    assert snap.hist_count["request_duration_us"]["simple"] == 110
    # stage series key folds the stage label in
    assert "simple|squeue" in snap.histograms["stage_duration_us"]


def test_window_quantiles_from_bucket_deltas():
    from client_tpu.perf.metrics_manager import (
        histogram_quantiles,
        parse_prometheus,
        summarize_metrics,
    )

    snaps = [parse_prometheus(_SCRAPE_BEFORE),
             parse_prometheus(_SCRAPE_AFTER)]
    quantiles = histogram_quantiles(summarize_metrics(snaps))
    entry = quantiles["request_duration_us|simple"]
    # window: 50 obs <= 100us, 50 in (100, 200]
    assert entry["count"] == 100
    assert entry["p50_us"] == pytest.approx(100.0)
    assert entry["p99_us"] == pytest.approx(198.0)
    assert entry["mean_us"] == pytest.approx(125.0)
    # A series born mid-window (absent from the first scrape) baselines
    # at 0, not at its first observed value.
    ttft = quantiles["stream_first_response_us|llm"]
    assert ttft["count"] == 4
    assert ttft["mean_us"] == pytest.approx(500.0)


def test_summary_entries_are_merge_additive():
    """hist! summary entries carry only a 'delta' leaf, the shape the
    profiler's stable-window merge sums generically."""
    from client_tpu.perf.metrics_manager import (
        parse_prometheus,
        summarize_metrics,
    )

    summary = summarize_metrics([parse_prometheus(_SCRAPE_BEFORE),
                                 parse_prometheus(_SCRAPE_AFTER)])
    hist_entries = {k: v for k, v in summary.items()
                    if k.startswith("hist!")}
    assert hist_entries
    for value in hist_entries.values():
        assert set(value) == {"delta"}


# -- profiler stream_stats plumbing ---------------------------------------


def test_normalize_and_delta_stream_stats():
    from client_tpu.perf.profiler import (
        _normalize_stats_entry,
        _numeric_delta,
    )

    entry = _normalize_stats_entry({
        "name": "llm", "version": "1", "inference_count": "5",
        "stream_stats": {
            "stream_count": "2", "response_count": "8",
            "first_response": {"count": "2", "ns": "1000"},
            "inter_response": {"count": "6", "ns": "3000"},
        },
    })
    assert entry["stream_stats"]["stream_count"] == 2
    assert entry["stream_stats"]["first_response"]["ns"] == 1000
    before = {"stream_stats": {"stream_count": 1, "response_count": 4,
                               "first_response": {"count": 1,
                                                  "ns": 400}}}
    delta = _numeric_delta(before, entry)
    assert delta["stream_stats"]["stream_count"] == 1
    assert delta["stream_stats"]["first_response"]["ns"] == 600


def test_print_report_histogram_lines(capsys):
    from client_tpu.perf.profiler import PerfStatus
    from client_tpu.perf.report import print_report

    status = PerfStatus()
    status.concurrency = 1
    status.completed_count = 10
    status.throughput = 100.0
    status.latency_percentiles = {50: 120.0, 99: 260.0}
    status.tpu_metrics = {
        "hist!request_duration_us|simple|le=100": {"delta": 5.0},
        "hist!request_duration_us|simple|le=+Inf": {"delta": 10.0},
        "hist!request_duration_us|simple|sum": {"delta": 1500.0},
        "hist!request_duration_us|simple|count": {"delta": 10.0},
        "hist!stream_first_response_us|simple|le=1000": {"delta": 4.0},
        "hist!stream_first_response_us|simple|le=+Inf": {"delta": 4.0},
        "hist!stream_first_response_us|simple|sum": {"delta": 2000.0},
        "hist!stream_first_response_us|simple|count": {"delta": 4.0},
    }
    print_report([status])
    out = capsys.readouterr().out
    assert "server simple /metrics histogram" in out
    assert "client p50 120 / p99 260" in out
    assert "TTFT p50" in out


# -- e2e: one core, both transports ---------------------------------------


@pytest.fixture(scope="module")
def stack():
    core = build_core(["simple", "repeat_int32"])
    grpc_handle = start_grpc_server(core=core, address="127.0.0.1:0")
    http_runner = start_http_server_thread(core, host="127.0.0.1",
                                           port=0)
    yield {"core": core, "grpc": grpc_handle.address,
           "http_port": http_runner.port}
    http_runner.stop()
    grpc_handle.stop()


def _simple_request(seed=0):
    in0 = InferInput("INPUT0", [16], "INT32")
    in0.set_data_from_numpy(np.arange(16, dtype=np.int32) + seed)
    in1 = InferInput("INPUT1", [16], "INT32")
    in1.set_data_from_numpy(np.ones(16, dtype=np.int32))
    return get_inference_request(
        model_name="simple", inputs=[in0, in1], model_version="",
        outputs=None, request_id="", sequence_id=0,
        sequence_start=False, sequence_end=False, priority=0,
        timeout=None)


def _hist_count(text, family, **labels):
    """The _count value of one histogram series in an exposition."""
    needle = "%s_count{%s}" % (
        family, ",".join('%s="%s"' % kv for kv in sorted(labels.items())))
    for line in text.splitlines():
        if line.startswith(needle):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def test_unary_requests_populate_request_and_stage_histograms(stack):
    core = stack["core"]
    before = _hist_count(core.metrics_text(), "tpu_request_duration_us",
                         model="simple")
    for i in range(5):
        core.infer(_simple_request(i))
    text = core.metrics_text()
    assert _hist_count(text, "tpu_request_duration_us",
                       model="simple") >= before + 5
    for stage in ("decode", "execute", "encode"):
        assert _hist_count(text, "tpu_stage_duration_us",
                           model="simple", stage=stage) >= 5
    errors, types, _ = lint_exposition(text)
    assert errors == []
    assert types.get("tpu_request_duration_us") == "histogram"


def test_stream_ttft_itl_over_grpc(stack):
    import queue as _queue

    core = stack["core"]
    before_text = core.metrics_text()
    before_first = _hist_count(before_text,
                               "tpu_stream_first_response_us",
                               model="repeat_int32")
    before_inter = _hist_count(before_text,
                               "tpu_stream_inter_response_us",
                               model="repeat_int32")
    with grpcclient.InferenceServerClient(stack["grpc"]) as client:
        results = _queue.Queue()
        client.start_stream(
            lambda result, error: results.put((result, error)))
        try:
            tensor = grpcclient.InferInput("IN", [4], "INT32")
            tensor.set_data_from_numpy(
                np.array([1, 2, 3, 4], dtype=np.int32))
            client.async_stream_infer("repeat_int32", [tensor])
            got = 0
            while got < 4:
                result, error = results.get(timeout=10)
                assert error is None
                got += 1
        finally:
            client.stop_stream()
        stats = client.get_inference_statistics("repeat_int32")
    text = stack["core"].metrics_text()
    # 1 first response + 3 inter-response gaps for a 4-element stream
    assert _hist_count(text, "tpu_stream_first_response_us",
                       model="repeat_int32") >= before_first + 1
    assert _hist_count(text, "tpu_stream_inter_response_us",
                       model="repeat_int32") >= before_inter + 3
    # ...and the means travel in ModelStatistics.stream_stats
    stream = stats.model_stats[0].stream_stats
    assert stream.response_count >= 4
    assert stream.first_response.count >= 1
    assert stream.inter_response.count >= 3
    assert stream.inter_response.ns > 0


def test_stream_ttft_itl_over_http_generate_stream(stack):
    import http.client as hc

    core = stack["core"]
    before = _hist_count(core.metrics_text(),
                         "tpu_stream_inter_response_us",
                         model="repeat_int32")
    conn = hc.HTTPConnection("127.0.0.1", stack["http_port"],
                             timeout=60)
    conn.request("POST", "/v2/models/repeat_int32/generate_stream",
                 body=json.dumps({"IN": [7, 8, 9]}),
                 headers={"Content-Type": "application/json"})
    response = conn.getresponse()
    payload = response.read().decode()
    conn.close()
    assert response.status == 200
    assert payload.count("data:") == 3
    text = core.metrics_text()
    assert _hist_count(text, "tpu_stream_inter_response_us",
                       model="repeat_int32") >= before + 2
    # stream_stats render over the HTTP statistics route too
    import urllib.request

    with urllib.request.urlopen(
            "http://127.0.0.1:%d/v2/models/repeat_int32/stats"
            % stack["http_port"], timeout=10) as resp:
        doc = json.loads(resp.read())
    stream = doc["model_stats"][0]["stream_stats"]
    assert int(stream["response_count"]) >= 3


def test_unary_through_stream_records_ttft(stack):
    core = stack["core"]
    before = _hist_count(core.metrics_text(),
                         "tpu_stream_first_response_us", model="simple")
    responses = list(core.stream_infer(_simple_request(3)))
    assert len(responses) == 1
    assert _hist_count(core.metrics_text(),
                       "tpu_stream_first_response_us",
                       model="simple") >= before + 1


def test_tenant_duration_is_a_histogram_not_a_bare_counter(stack):
    core = stack["core"]
    request = _simple_request(11)
    request.parameters["tenant"].string_param = "acme-corp"
    core.infer(request)
    text = core.metrics_text()
    assert 'tpu_tenant_request_duration_us_bucket{tenant="acme-corp"' \
        in text
    assert 'tpu_tenant_request_duration_us_count{tenant="acme-corp"' \
        in text
    # the PR-7 sum-only counter sample must be gone
    for line in text.splitlines():
        assert not line.startswith("tpu_tenant_request_duration_us{")
    errors, types, _ = lint_exposition(text)
    assert errors == []
    assert types["tpu_tenant_request_duration_us"] == "histogram"


def test_metrics_content_negotiation_over_http(stack):
    """Exemplars + '# EOF' are OpenMetrics syntax: served only when
    the scraper negotiates that flavor via Accept; the default
    text-format response never carries either."""
    import urllib.request

    url = "http://127.0.0.1:%d/metrics" % stack["http_port"]
    with urllib.request.urlopen(url, timeout=10) as resp:
        plain = resp.read().decode()
        plain_type = resp.headers.get("Content-Type", "")
    assert "# EOF" not in plain
    assert "# {" not in plain
    assert "text/plain" in plain_type
    request = urllib.request.Request(
        url, headers={"Accept": "application/openmetrics-text"})
    with urllib.request.urlopen(request, timeout=10) as resp:
        openmetrics = resp.read().decode()
        om_type = resp.headers.get("Content-Type", "")
    assert openmetrics.rstrip().endswith("# EOF")
    assert "application/openmetrics-text" in om_type
    errors, _, _ = lint_exposition(openmetrics)
    assert errors == []


def test_telemetry_survives_concurrent_load_lint_clean(stack):
    core = stack["core"]

    def worker(offset):
        for i in range(10):
            core.infer(_simple_request(offset + i))

    threads = [threading.Thread(target=worker, args=(i * 100,))
               for i in range(4)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    errors, _, _ = lint_exposition(core.metrics_text())
    assert errors == []


# -- quantile fidelity + exemplar join on a seeded-latency model ----------


def test_bucket_p99_matches_trace_p99_and_exemplar_joins(tmp_path):
    from client_tpu.perf.metrics_manager import (
        histogram_quantiles,
        parse_prometheus,
        summarize_metrics,
    )
    from client_tpu.server import chaos

    core = build_core(["simple"])
    trace_file = tmp_path / "trace.jsonl"
    try:
        chaos.configure(chaos.ChaosConfig(latency_ms=20,
                                          models={"simple"}))
        core.trace_setting("", {
            "trace_level": ["TIMESTAMPS"], "trace_rate": ["1"],
            "trace_count": ["-1"], "log_frequency": ["1"],
            "trace_file": [str(trace_file)],
            "trace_mode": ["compact"]})
        before = core.metrics_text()
        for i in range(30):
            core.infer(_simple_request(i))
        # The OpenMetrics flavor (negotiated via Accept on the HTTP
        # front-ends) carries the exemplars; the plain flavor must
        # stay exemplar-free even while tracing is on.
        after = core.metrics_text(openmetrics=True)
        assert after.rstrip().endswith("# EOF")
        assert "# {" not in core.metrics_text()
        core.trace_setting("", {"trace_level": ["OFF"]})
        records = [json.loads(line)
                   for line in trace_file.read_text().splitlines()
                   if line.strip()]
        assert len(records) == 30
        roots_us = []
        trace_ids = set()
        for record in records:
            trace_ids.add(record["trace_id"])
            root = next(s for s in record["spans"]
                        if s["name"] == "request")
            roots_us.append((root["end_ns"] - root["start_ns"])
                            / 1000.0)
        roots_us.sort()
        trace_p99 = roots_us[int(len(roots_us) * 0.99) - 1]
        quantiles = histogram_quantiles(summarize_metrics(
            [parse_prometheus(before), parse_prometheus(after)]))
        entry = quantiles["request_duration_us|simple"]
        assert entry["count"] == 30
        # The estimate must land within one bucket width of the
        # trace-derived p99 (the ladder's resolution bound).
        assert abs(entry["p99_us"] - trace_p99) \
            <= bucket_width_us(trace_p99)
        # Exemplar -> trace join: the hot bucket's exemplar names a
        # trace id that exists in the trace file.
        exemplar_ids = set()
        for line in after.splitlines():
            if line.startswith("tpu_request_duration_us_bucket") \
                    and "# {" in line:
                exemplar_ids.add(
                    line.split('trace_id="', 1)[1].split('"', 1)[0])
        assert exemplar_ids, "no exemplars on a trace_rate=1 run"
        assert exemplar_ids & trace_ids
        # The plain text-format flavor stays exemplar-free after
        # tracing is off too (stored exemplars serve only negotiated
        # OpenMetrics scrapes).
        assert "# {" not in core.metrics_text()
    finally:
        chaos.configure(None)
        core.shutdown()


# -- genai server-side join -----------------------------------------------


_GENAI_BEFORE = """\
# TYPE tpu_stream_first_response_us histogram
tpu_stream_first_response_us_bucket{model="llm",le="10000"} 0
tpu_stream_first_response_us_bucket{model="llm",le="20000"} 0
tpu_stream_first_response_us_bucket{model="llm",le="+Inf"} 0
tpu_stream_first_response_us_sum{model="llm"} 0
tpu_stream_first_response_us_count{model="llm"} 0
"""

_GENAI_AFTER = """\
# TYPE tpu_stream_first_response_us histogram
tpu_stream_first_response_us_bucket{model="llm",le="10000"} 8
tpu_stream_first_response_us_bucket{model="llm",le="20000"} 16
tpu_stream_first_response_us_bucket{model="llm",le="+Inf"} 16
tpu_stream_first_response_us_sum{model="llm"} 200000.0
tpu_stream_first_response_us_count{model="llm"} 16
# TYPE tpu_stream_inter_response_us histogram
tpu_stream_inter_response_us_bucket{model="llm",le="1000"} 50
tpu_stream_inter_response_us_bucket{model="llm",le="2000"} 100
tpu_stream_inter_response_us_bucket{model="llm",le="+Inf"} 100
tpu_stream_inter_response_us_sum{model="llm"} 120000.0
tpu_stream_inter_response_us_count{model="llm"} 100
"""


def test_genai_parse_server_histograms_canned_scrape():
    from client_tpu.genai.metrics import parse_server_histograms

    rows = parse_server_histograms(_GENAI_BEFORE, _GENAI_AFTER, "llm")
    ttft = rows["server_time_to_first_token_ms"]
    assert ttft["p50"] == pytest.approx(10.0)     # 10000 us
    assert ttft["mean"] == pytest.approx(12.5)    # 200000/16 us
    itl = rows["server_inter_token_latency_ms"]
    assert itl["p50"] == pytest.approx(1.0)
    assert itl["p99"] == pytest.approx(1.98)
    # unknown model: no rows, caller prints a notice instead
    assert parse_server_histograms(_GENAI_BEFORE, _GENAI_AFTER,
                                   "other") == {}


def test_genai_console_report_includes_server_rows():
    from client_tpu.genai.exporters import console_report
    from client_tpu.genai.metrics import (
        LLMMetrics,
        Statistics,
        parse_server_histograms,
    )

    metrics = LLMMetrics(
        time_to_first_token_ns=[15_000_000, 16_000_000],
        inter_token_latency_ns=[1_200_000] * 4,
        request_latency_ns=[30_000_000, 32_000_000],
        output_token_counts=[4, 4],
        benchmark_duration_s=1.0)
    stats = Statistics(metrics)
    stats.stats.update(parse_server_histograms(
        _GENAI_BEFORE, _GENAI_AFTER, "llm"))
    report = console_report(stats)
    assert "server_time_to_first_token_ms" in report
    assert "server_inter_token_latency_ms" in report
    # rows with partial columns render "-" cells, never NaN
    assert "nan" not in report


def test_genai_html_report_includes_server_rows(tmp_path):
    from client_tpu.genai.html_report import generate_html_report
    from client_tpu.genai.metrics import (
        LLMMetrics,
        Statistics,
        parse_server_histograms,
    )

    metrics = LLMMetrics(
        time_to_first_token_ns=[15_000_000],
        inter_token_latency_ns=[1_200_000] * 3,
        request_latency_ns=[30_000_000],
        output_token_counts=[4],
        benchmark_duration_s=1.0,
        itl_sequences_ns=[[1_200_000] * 3])
    stats = Statistics(metrics)
    stats.stats.update(parse_server_histograms(
        _GENAI_BEFORE, _GENAI_AFTER, "llm"))
    path = generate_html_report([stats], str(tmp_path), title="t")
    html_text = open(path).read()
    assert "server TTFT p99 (ms)" in html_text
    assert "server_time_to_first_token_ms" in html_text
