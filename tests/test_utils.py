"""Unit tests for client_tpu.utils serialization + dtype mapping.

Mirrors the coverage intent of the reference's utils tests (BYTES and
BF16 round-trips, dtype table completeness)."""

import numpy as np
import pytest

import ml_dtypes

from client_tpu.utils import (
    InferenceServerException,
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    np_to_wire_dtype,
    serialize_bf16_tensor,
    serialize_byte_tensor,
    serialized_byte_size,
    tensor_byte_size,
    wire_to_np_dtype,
)

ALL_FIXED = [
    ("BOOL", np.bool_), ("INT8", np.int8), ("INT16", np.int16),
    ("INT32", np.int32), ("INT64", np.int64), ("UINT8", np.uint8),
    ("UINT16", np.uint16), ("UINT32", np.uint32), ("UINT64", np.uint64),
    ("FP16", np.float16), ("FP32", np.float32), ("FP64", np.float64),
]


@pytest.mark.parametrize("wire,np_t", ALL_FIXED)
def test_dtype_roundtrip(wire, np_t):
    assert np_to_wire_dtype(np_t) == wire
    assert wire_to_np_dtype(wire) == np.dtype(np_t)


def test_bf16_dtype():
    assert np_to_wire_dtype(ml_dtypes.bfloat16) == "BF16"
    assert wire_to_np_dtype("BF16") == np.dtype(ml_dtypes.bfloat16)


def test_bytes_dtype():
    assert np_to_wire_dtype(np.object_) == "BYTES"
    assert np_to_wire_dtype("S10") == "BYTES"
    assert wire_to_np_dtype("BYTES") == np.dtype(np.object_)


def test_byte_tensor_roundtrip():
    arr = np.array([b"abc", b"", b"hello world", "unicodeé".encode()],
                   dtype=np.object_).reshape(2, 2)
    enc = serialize_byte_tensor(arr)
    dec = deserialize_bytes_tensor(enc.tobytes()).reshape(2, 2)
    assert dec.tolist() == arr.tolist()


def test_byte_tensor_from_str():
    arr = np.array(["a", "bb"], dtype=np.object_)
    enc = serialize_byte_tensor(arr).tobytes()
    dec = deserialize_bytes_tensor(enc)
    assert dec.tolist() == [b"a", b"bb"]
    assert serialized_byte_size(arr) == len(enc) == 4 + 1 + 4 + 2


def test_byte_tensor_empty():
    assert serialize_byte_tensor(np.array([], dtype=np.object_)).size == 0
    assert deserialize_bytes_tensor(b"").size == 0


def test_byte_tensor_malformed():
    with pytest.raises(InferenceServerException):
        deserialize_bytes_tensor(b"\x05\x00\x00\x00ab")  # overrun
    with pytest.raises(InferenceServerException):
        deserialize_bytes_tensor(b"\x01\x00")  # truncated prefix


def test_bf16_roundtrip():
    x = np.array([[1.5, -2.25], [0.0, 3e8]], dtype=ml_dtypes.bfloat16)
    enc = serialize_bf16_tensor(x)
    assert enc.dtype == np.uint8 and enc.size == x.size * 2
    dec = deserialize_bf16_tensor(enc.tobytes()).reshape(x.shape)
    assert np.array_equal(dec, x)


def test_bf16_from_float32():
    x = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    enc = serialize_bf16_tensor(x)
    dec = deserialize_bf16_tensor(enc.tobytes())
    assert np.allclose(dec.astype(np.float32), x)


def test_tensor_byte_size():
    assert tensor_byte_size("FP32", [2, 3]) == 24
    assert tensor_byte_size("BF16", [4]) == 8
    assert tensor_byte_size("BYTES", [4]) == -1
