"""Flight recorder + SLO engine + /v2/debug (PR 14): ring-buffer
budget semantics under concurrent capture, retroactive-keep decisions
for every trigger, SLO burn-rate golden math across window
boundaries, the live-introspection endpoint over both HTTP front-ends
and gRPC, and the `slo` ModelConfig block's rendering round-trip."""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from client_tpu._infer_common import InferInput
from client_tpu.grpc._utils import get_inference_request
from client_tpu.server import chaos
from client_tpu.server import tracing as spantrace
from client_tpu.server.app import build_core, start_grpc_server
from client_tpu.server.flight import FlightRecorder
from client_tpu.server.http_embed import http_call
from client_tpu.server.http_server import start_http_server_thread
from client_tpu.server.slo import (
    SloEngine,
    SloSample,
    SloTarget,
    count_at_or_below,
    wants_slo,
)
from client_tpu.utils import InferenceServerException

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
from metrics_lint import lint_debug_snapshot, lint_exposition  # noqa: E402


def _finished_trace(duration_ns: int = 1_000_000,
                    error: str = None) -> spantrace.RequestTrace:
    trace = spantrace.RequestTrace(attrs={"model": "m"})
    trace.add_timed(spantrace.SPAN_DECODE, trace.root.start_ns,
                    trace.root.start_ns + duration_ns // 2)
    trace.root.end_ns = trace.root.start_ns + duration_ns
    if error:
        trace.root.attrs["error"] = error
    return trace


def _simple_request(model_name: str, seed: int = 0,
                    batched: bool = False):
    shape = [1, 16] if batched else [16]
    a = np.full(shape, seed % 97, dtype=np.int32)
    b = np.arange(16, dtype=np.int32).reshape(shape)
    t0 = InferInput("INPUT0", shape, "INT32")
    t0.set_data_from_numpy(a)
    t1 = InferInput("INPUT1", shape, "INT32")
    t1.set_data_from_numpy(b)
    return get_inference_request(model_name=model_name,
                                 inputs=[t0, t1], outputs=None)


class _Model:
    """Bare model stub for recorder-unit keep decisions."""

    def __init__(self, flight_slow_us=0):
        self.flight_slow_us = flight_slow_us


# -- ring buffer ----------------------------------------------------------


def test_ring_count_budget_overwrites_oldest():
    recorder = FlightRecorder(enabled=True, max_entries=3,
                              max_bytes=1 << 30)
    model = _Model(flight_slow_us=1)
    for i in range(5):
        trace = _finished_trace(duration_ns=10_000_000)
        recorder.observe(model, "m", "req-%d" % i, trace)
    records = recorder.snapshot("m")
    assert [r["request_id"] for r in records] == \
        ["req-2", "req-3", "req-4"]
    stats = recorder.stats()["m"]
    assert stats["entries"] == 3
    assert stats["kept_total"] == 5
    assert stats["overwritten_total"] == 2


def test_ring_byte_budget_overwrites_oldest_and_tracks_bytes():
    # learn one record's serialized size with an unconstrained probe
    probe = FlightRecorder(enabled=True)
    model = _Model(flight_slow_us=1)
    probe.observe(model, "m", "a", _finished_trace(10_000_000))
    one = probe.stats()["m"]["bytes"]
    # a budget that fits ONE record but not two
    recorder = FlightRecorder(enabled=True, max_entries=10_000,
                              max_bytes=one + one // 2)
    recorder.observe(model, "m", "a", _finished_trace(10_000_000))
    recorder.observe(model, "m", "b", _finished_trace(10_000_000))
    records = recorder.snapshot("m")
    assert [r["request_id"] for r in records] == ["b"]
    stats = recorder.stats()["m"]
    assert stats["overwritten_total"] == 1
    assert stats["oversized_total"] == 0
    # accounted bytes match the resident entries exactly
    assert stats["bytes"] == sum(
        len(json.dumps(r, separators=(",", ":"), default=str)) + 64
        for r in recorder.snapshot("m"))


def test_ring_budgets_hold_under_concurrent_capture():
    recorder = FlightRecorder(enabled=True, max_entries=16,
                              max_bytes=64 * 1024)
    model = _Model(flight_slow_us=1)
    threads = 8
    per_thread = 50

    def worker(index):
        for i in range(per_thread):
            trace = _finished_trace(duration_ns=10_000_000)
            recorder.observe(model, "m", "t%d-%d" % (index, i), trace)

    pool = [threading.Thread(target=worker, args=(t,))
            for t in range(threads)]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()
    stats = recorder.stats()["m"]
    assert stats["kept_total"] == threads * per_thread
    assert stats["entries"] <= 16
    assert stats["bytes"] <= 64 * 1024
    assert stats["entries"] + stats["overwritten_total"] == \
        stats["kept_total"]


# -- retroactive keep decisions ------------------------------------------


@pytest.mark.parametrize("status,reason", [
    ("INTERNAL", "error"),
    ("UNAVAILABLE", "shed"),
    ("DEADLINE_EXCEEDED", "timeout"),
    ("RESOURCE_EXHAUSTED", "quota"),
])
def test_keep_reason_per_status(status, reason):
    recorder = FlightRecorder(enabled=True)
    kept = recorder.observe(_Model(), "m", "r", _finished_trace(),
                            error="boom", status=status)
    assert kept == reason
    record = recorder.snapshot("m")[-1]
    assert record["reason"] == reason
    assert record["status"] == status
    assert record["error"] == "boom"
    assert record["spans"][0]["name"] == "request"


def test_keep_slow_absolute_threshold():
    recorder = FlightRecorder(enabled=True)
    model = _Model(flight_slow_us=5_000)
    assert recorder.observe(model, "m", "fast",
                            _finished_trace(1_000_000)) is None
    kept = recorder.observe(model, "m", "slow",
                            _finished_trace(10_000_000))
    assert kept == "slow"
    record = recorder.snapshot("m")[-1]
    assert record["threshold_us"] == 5_000
    assert record["threshold_source"] == "absolute"


def test_keep_slow_derived_p99_threshold():
    from client_tpu.server.telemetry import ServerTelemetry

    telemetry = ServerTelemetry(enabled=True)
    for _ in range(200):
        telemetry.observe_request("m", 100.0)  # a tight population
    recorder = FlightRecorder(enabled=True, telemetry=telemetry)
    model = _Model(flight_slow_us=0)  # 0 -> derive from the histogram
    threshold, source = recorder.slow_threshold_us(model, "m")
    assert source == "derived_p99"
    assert 0 < threshold < 1_000
    assert recorder.observe(model, "m", "fast",
                            _finished_trace(50_000)) is None
    kept = recorder.observe(model, "m", "slow",
                            _finished_trace(50_000_000))
    assert kept == "slow"
    assert recorder.snapshot("m")[-1]["threshold_source"] == \
        "derived_p99"


def test_derived_threshold_needs_samples():
    from client_tpu.server.telemetry import ServerTelemetry

    telemetry = ServerTelemetry(enabled=True)
    telemetry.observe_request("m", 100.0)  # << MIN_DERIVED_SAMPLES
    recorder = FlightRecorder(enabled=True, telemetry=telemetry)
    threshold, source = recorder.slow_threshold_us(_Model(), "m")
    assert (threshold, source) == (0, "none")
    # nothing keeps while the estimate is untrusted
    assert recorder.observe(_Model(), "m", "r",
                            _finished_trace(50_000_000)) is None


def test_disabled_recorder_keeps_nothing():
    recorder = FlightRecorder(enabled=False)
    assert recorder.observe(_Model(flight_slow_us=1), "m", "r",
                            _finished_trace(10_000_000),
                            error="x", status="INTERNAL") is None
    assert recorder.snapshot() == []


def test_mark_incident_stamps_resident_records():
    recorder = FlightRecorder(enabled=True)
    model = _Model(flight_slow_us=1)
    recorder.observe(model, "m", "a", _finished_trace(10_000_000))
    recorder.observe(model, "m", "b", _finished_trace(10_000_000))
    stamped = recorder.mark_incident("m", "breaker_trip replica=2")
    assert stamped == 2
    for record in recorder.snapshot("m"):
        assert record["incidents"][0]["label"] == \
            "breaker_trip replica=2"
    # a later keep is NOT stamped by the earlier incident
    recorder.observe(model, "m", "c", _finished_trace(10_000_000))
    assert recorder.snapshot("m")[-1]["incidents"] == []


def test_oversized_record_is_dropped_not_retained():
    """A single keep larger than max_bytes must neither destroy the
    older evidence nor defeat the budget by staying resident (a
    memory-DoS lever with client-fed payloads): it is dropped and
    counted, everything already retained stays."""
    recorder = FlightRecorder(enabled=True, max_entries=100,
                              max_bytes=600)
    model = _Model(flight_slow_us=1)
    recorder.observe(model, "m", "small", _finished_trace(10_000_000))
    big = _finished_trace(10_000_000, error="x" * 2000)
    recorder.observe(model, "m", "big", big, error="x" * 2000,
                     status="INTERNAL")
    records = recorder.snapshot("m")
    assert [r["request_id"] for r in records] == ["small"]
    stats = recorder.stats()["m"]
    assert stats["oversized_total"] == 1
    assert stats["bytes"] <= 600  # the budget holds


def test_client_controlled_strings_are_clamped():
    from client_tpu.server.flight import (
        MAX_ERROR_CHARS,
        MAX_ID_CHARS,
        MAX_NAME_CHARS,
    )

    recorder = FlightRecorder(enabled=True)
    recorder.observe(_Model(), "m" * 10_000, "r" * 10_000,
                     _finished_trace(), error="e" * 100_000,
                     status="INTERNAL")
    (name, snap), = recorder.stats().items()
    assert len(name) == MAX_NAME_CHARS
    record = recorder.snapshot(name)[0]
    assert len(record["request_id"]) == MAX_ID_CHARS
    assert len(record["error"]) == MAX_ERROR_CHARS


def test_mark_incident_caps_stamps_and_accounts_bytes():
    from client_tpu.server.flight import MAX_INCIDENT_STAMPS

    recorder = FlightRecorder(enabled=True)
    model = _Model(flight_slow_us=1)
    recorder.observe(model, "m", "r", _finished_trace(10_000_000))
    bytes_before = recorder.stats()["m"]["bytes"]
    for i in range(MAX_INCIDENT_STAMPS * 3):
        recorder.mark_incident("m", "flap %d" % i)
    record = recorder.snapshot("m")[0]
    # capped: the oldest stamps rolled off, the newest survive
    assert len(record["incidents"]) == MAX_INCIDENT_STAMPS
    assert record["incidents"][-1]["label"] == \
        "flap %d" % (MAX_INCIDENT_STAMPS * 3 - 1)
    bytes_after = recorder.stats()["m"]["bytes"]
    # accounted, and bounded by the cap (not by the flap count)
    assert bytes_before < bytes_after <= bytes_before + 60 * (
        MAX_INCIDENT_STAMPS + 1)


def test_stamped_record_eviction_leaves_no_phantom_bytes():
    """A record stamped by mark_incident grows its accounted size;
    evicting it must subtract that grown size — churning stamped
    records out of the ring must leave bytes == exact resident sum."""
    recorder = FlightRecorder(enabled=True, max_entries=4,
                              max_bytes=1 << 30)
    model = _Model(flight_slow_us=1)
    for i in range(4):
        recorder.observe(model, "m", "old-%d" % i,
                         _finished_trace(10_000_000))
    recorder.mark_incident("m", "burn")
    for i in range(8):  # churn every stamped record out
        recorder.observe(model, "m", "new-%d" % i,
                         _finished_trace(10_000_000))
    stats = recorder.stats()["m"]
    resident = sum(
        len(json.dumps(r, separators=(",", ":"), default=str)) + 64
        for r in recorder.snapshot("m"))
    assert stats["bytes"] == resident  # no stamp residue


def test_quota_and_drain_rejects_land_in_flight_ring():
    """Admission-stage failures (tenant quota 429, drain/unknown-model
    rejects) fire before the scratch-capture path — they must still
    be retained with their dedicated keep reasons."""
    core = build_core(["simple_slo"],
                      tenant_quotas="default=rate:1000,concurrency:1")
    try:
        request = _simple_request("simple_slo")
        request.parameters["tenant"].string_param = "t1"
        # exhaust t1's concurrency slot so the next request rejects
        core.tenant_quotas.acquire("t1")
        caller_trace = "00-%032x-%016x-01" % (0xabc123, 0x42)
        with pytest.raises(InferenceServerException):
            core.infer(request, trace_context=caller_trace)
        records = core.flight.snapshot("simple_slo")
        assert records and records[-1]["reason"] == "quota"
        assert records[-1]["status"] == "RESOURCE_EXHAUSTED"
        # the record adopted the caller's W3C trace id (joinable)
        assert records[-1]["trace_id"] == "%032x" % 0xabc123
        # unknown-model reject (NOT_FOUND) retained too
        with pytest.raises(InferenceServerException):
            core.infer(_simple_request("no_such_model"))
        bogus = core.flight.snapshot("no_such_model")
        assert bogus and bogus[-1]["reason"] == "error"
    finally:
        core.shutdown()


def test_ring_count_cap_folds_into_overflow():
    from client_tpu.server.flight import MAX_RINGS, OVERFLOW_RING

    recorder = FlightRecorder(enabled=True)
    for i in range(MAX_RINGS + 5):
        recorder.observe(_Model(), "model-%d" % i, "r",
                         _finished_trace(), error="x",
                         status="INTERNAL")
    stats = recorder.stats()
    assert len(stats) == MAX_RINGS + 1  # the cap + the overflow ring
    assert stats[OVERFLOW_RING]["kept_total"] == 5


def test_unmonitorable_latency_objective_fails_verdict(slo_core):
    """CLIENT_TPU_TELEMETRY=off freezes the latency histograms; a
    declared latency objective must then fail the verdict loudly,
    never report burn 0 / healthy (the silent-PASS trap)."""
    core = slo_core
    core.infer(_simple_request("simple_slo"))
    assert core.slo.evaluate(force_sample=True)["simple_slo"]["healthy"]
    core.telemetry.enabled = False
    try:
        verdict = core.slo.evaluate(force_sample=True)["simple_slo"]
        assert verdict["monitored"] is False
        assert verdict["healthy"] is False
        assert "tpu_slo_healthy{model=\"simple_slo\"} 0" in \
            core.metrics_text()
    finally:
        core.telemetry.enabled = True
    verdict = core.slo.evaluate(force_sample=True)["simple_slo"]
    assert verdict["monitored"] and verdict["healthy"]


def test_flush_chrome_writes_loadable_events(tmp_path):
    recorder = FlightRecorder(enabled=True)
    recorder.observe(_Model(flight_slow_us=1), "m", "r",
                     _finished_trace(10_000_000))
    path = tmp_path / "flight.json"
    assert recorder.flush_chrome(str(path)) == 1
    text = path.read_text()
    # chrome-trace format allows the missing close bracket
    events = json.loads(text.rstrip().rstrip(",") + "]")
    names = {e.get("name") for e in events}
    assert "request" in names and "decode" in names
    args = [e["args"] for e in events if e.get("ph") == "X"]
    assert all(a["request_id"] == "r" for a in args)
    # the ring is NOT cleared by an export
    assert recorder.snapshot("m")


# -- in-flight registry ---------------------------------------------------


def test_in_flight_registry_tracks_age_and_stage():
    recorder = FlightRecorder(enabled=True)
    trace = spantrace.RequestTrace(attrs={"model": "m"})
    token = recorder.track("m", "req-1", trace)
    live = recorder.in_flight()
    assert len(live) == 1
    assert live[0]["request_id"] == "req-1"
    assert live[0]["stage"] == "admitted"
    trace.add_timed(spantrace.SPAN_DECODE, trace.root.start_ns,
                    trace.root.start_ns + 1000)
    assert recorder.in_flight()[0]["stage"] == "decode"
    recorder.untrack(token)
    assert recorder.in_flight() == []


# -- SLO engine golden math -----------------------------------------------


def test_count_at_or_below_interpolates():
    buckets = [(100.0, 10.0), (200.0, 30.0), (float("inf"), 40.0)]
    assert count_at_or_below(buckets, 100.0) == pytest.approx(10.0)
    # halfway through the (100, 200] bucket -> half its 20 counts
    assert count_at_or_below(buckets, 150.0) == pytest.approx(20.0)
    # +Inf-bucket observations can never be placed below a finite
    # threshold: they count as OVER target (conservative — the SLO
    # never credits unbounded observations as good)
    assert count_at_or_below(buckets, 1e9) == pytest.approx(30.0)
    assert count_at_or_below(buckets, 0.0) == pytest.approx(0.0)


def _engine(samples_by_model, targets, now, **kwargs):
    """An engine fed by canned cumulative samples: collect_fn pops the
    next sample for the model each time it is called."""
    def targets_fn():
        return [(name, target, None) for name, target in targets.items()]

    def collect_fn(name, target):
        queue = samples_by_model[name]
        sample = queue[0] if len(queue) == 1 else queue.pop(0)
        return SloSample(0.0, **sample)

    clock = {"now": now[0]}
    engine = SloEngine(targets_fn, collect_fn,
                       now_fn=lambda: clock["now"], **kwargs)
    return engine, clock


def test_burn_rate_golden_math_across_window_boundaries():
    """Fast window 60 s, slow 1000 s. A bad burst lands before the
    t=100 sample; clean traffic follows. At t=700 the fast window's
    baseline (the newest sample at least 60 s old) post-dates the
    burst, so fast burn is 0, while the slow window ramps back to the
    engine-start zero seed and still spans the burst — the boundary
    behavior the multi-window methodology exists for."""
    target = SloTarget(availability=0.99)  # allowed bad fraction 1%
    # cumulative (ok, bad): the burst has put 50 bad / 50 ok by t=100
    feed = {"m": [
        {"ok_count": 50.0, "bad_count": 50.0},    # sampled at t=100
        {"ok_count": 1050.0, "bad_count": 50.0},  # sampled at t=650
        {"ok_count": 1150.0, "bad_count": 50.0},  # fresh at t=700
    ]}
    engine, clock = _engine(feed, {"m": target}, [0.0],
                            fast_window_s=60.0, slow_window_s=1000.0,
                            min_sample_interval_s=0.0)
    clock["now"] = 100.0
    engine.sample(force=True)      # burst cumulative recorded
    clock["now"] = 650.0
    engine.sample(force=True)      # clean history point
    clock["now"] = 700.0
    verdict = engine.evaluate()["m"]
    # fast baseline: newest sample <= t=640 is the t=100 one; the
    # delta from there is 1100 ok / 0 bad -> burn 0 (the burst itself
    # is cumulative IN the baseline, so it is excluded)
    assert verdict["burn"]["fast"] == pytest.approx(0.0)
    # slow window (1000 s) ramps to the zero seed at t=0: delta
    # 1150 ok + 50 bad -> 4.17% bad against the 1% allowance
    assert verdict["burn"]["slow"] == pytest.approx(
        (50.0 / 1200.0) / 0.01, rel=1e-6)
    # fast calm + slow burning -> still healthy (multi-window rule)
    assert verdict["healthy"] is True
    assert verdict["budget_remaining"] == pytest.approx(
        max(0.0, 1.0 - verdict["burn"]["slow"]))


def test_burn_rate_latency_objective_and_unhealthy_transition():
    target = SloTarget(p99_latency_us=1000)
    # 10% of requests over the 1 ms target -> burn 10x (allowed 1%)
    feed = {"m": [
        {"latency_total": 100.0, "latency_good": 90.0},
    ]}
    incidents = []
    engine, clock = _engine(
        feed, {"m": target}, [10.0],
        fast_window_s=60.0, slow_window_s=600.0,
        min_sample_interval_s=0.0,
        incident_hook=lambda m, label: incidents.append((m, label)))
    verdict = engine.evaluate()["m"]
    assert verdict["burn"]["fast"] == pytest.approx(10.0)
    assert verdict["burn"]["slow"] == pytest.approx(10.0)
    assert verdict["objectives"]["p99_latency_us"] == \
        pytest.approx(10.0)
    # both windows burn > 1 -> unhealthy, and the transition fired
    # the incident hook exactly once
    assert verdict["healthy"] is False
    assert incidents == [("m", "slo_burn fast=10.00 slow=10.00")]
    engine.evaluate()
    assert len(incidents) == 1  # no re-fire while still unhealthy


def test_burn_rate_max_over_objectives():
    target = SloTarget(p99_latency_us=1000, availability=0.999)
    feed = {"m": [{
        "latency_total": 1000.0, "latency_good": 995.0,  # 0.5% -> 0.5x
        "ok_count": 990.0, "bad_count": 10.0,  # 1% bad / 0.1% -> 10x
    }]}
    engine, _clock = _engine(feed, {"m": target}, [10.0],
                             min_sample_interval_s=0.0)
    verdict = engine.evaluate()["m"]
    assert verdict["burn"]["fast"] == pytest.approx(10.0, rel=1e-3)
    assert verdict["objectives"]["availability"] == \
        pytest.approx(10.0, rel=1e-3)
    assert verdict["objectives"]["p99_latency_us"] == \
        pytest.approx(0.5, rel=1e-3)


def test_store_sample_rejects_out_of_order_timestamps():
    """The shared locked store guards ts ordering: a racing caller's
    stale-timestamp sample must not land after a newer one (the
    window-baseline scan assumes ts-sorted history)."""
    engine = SloEngine(lambda: [], lambda n, t: SloSample(0.0),
                       now_fn=lambda: 0.0)
    engine._store_sample("m", SloSample(10.0), force=True)
    history = engine._store_sample("m", SloSample(5.0), force=True)
    assert [s.ts for s in history] == [0.0, 10.0]  # stale ts dropped


def test_wants_slo_and_target_of():
    assert not wants_slo(_Model())
    model = _Model()
    model.slo_availability = 0.999
    assert wants_slo(model)
    target = SloTarget.of(model)
    assert target.availability == 0.999
    assert target.p99_latency_us == 0


# -- e2e: flight capture through the core ---------------------------------


@pytest.fixture()
def slo_core():
    core = build_core(["simple_slo"])
    yield core
    chaos.configure(None)
    core.shutdown()


def test_e2e_error_and_slow_keeps_at_trace_rate_zero(slo_core):
    core = slo_core
    for i in range(4):
        core.infer(_simple_request("simple_slo", i))  # warm
    kept_before = core.flight.stats().get("simple_slo", {}).get(
        "kept_total", 0)
    chaos.configure_from_spec("error_rate=1.0,seed=5")
    with pytest.raises(InferenceServerException):
        core.infer(_simple_request("simple_slo"))
    chaos.configure_from_spec("latency_ms=120,seed=5")
    core.infer(_simple_request("simple_slo"))
    chaos.configure(None)
    records = core.flight.snapshot("simple_slo")
    fresh = records[kept_before:]
    reasons = [r["reason"] for r in fresh]
    assert reasons == ["shed", "slow"]
    slow = fresh[-1]
    names = {span["name"] for span in slow["spans"]}
    # the kept trace carries the full span tree at trace_rate=0
    assert {"request", "decode", "device_execute", "encode"} <= names
    assert slow["duration_us"] >= 100_000
    assert slow["threshold_source"] == "absolute"


def test_e2e_timeout_keep_through_single_flight(slo_core):
    """A DEADLINE_EXCEEDED (follower deadline) lands in the ring as a
    timeout keep — driven through the real core error path."""
    core = slo_core
    request = _simple_request("simple_slo")
    request.parameters["timeout"].int64_param = 1  # 1 us deadline
    chaos.configure_from_spec("latency_ms=50,seed=5")
    # direct path ignores queue deadlines; emulate the batcher's
    # timeout by observing directly what core would feed
    chaos.configure(None)
    trace = _finished_trace(error="expired")
    kept = core.flight.observe(
        core.repository.get("simple_slo"), "simple_slo", request.id,
        trace, error="expired", status="DEADLINE_EXCEEDED")
    assert kept == "timeout"


def test_e2e_sampled_trace_also_lands_in_flight(slo_core, tmp_path):
    """trace_rate=1 sampling and flight retention are not exclusive:
    a sampled request that errors is both emitted to the trace file
    and kept in the ring, under the SAME trace id."""
    core = slo_core
    trace_file = tmp_path / "trace.jsonl"
    core.trace_setting("", {
        "trace_level": ["TIMESTAMPS"], "trace_rate": ["1"],
        "trace_file": [str(trace_file)], "log_frequency": ["1"],
    })
    chaos.configure_from_spec("error_rate=1.0,seed=5")
    with pytest.raises(InferenceServerException):
        core.infer(_simple_request("simple_slo"))
    chaos.configure(None)
    core.trace_setting("", {"trace_level": ["OFF"]})
    record = core.flight.snapshot("simple_slo")[-1]
    emitted = [json.loads(line)
               for line in trace_file.read_text().splitlines() if line]
    assert any(e["trace_id"] == record["trace_id"] for e in emitted)


def test_stream_error_keeps_via_root_attrs():
    core = build_core(["repeat_int32"])
    try:
        def stream_request(input_name):
            request = get_inference_request(model_name="repeat_int32",
                                            inputs=[], outputs=None)
            tensor = request.inputs.add()
            tensor.name = input_name
            tensor.datatype = "INT32"
            tensor.shape.extend([4])
            request.raw_input_contents.append(
                np.arange(4, dtype=np.int32).tobytes())
            return request

        # A decode failure rides the stream as an error response, not
        # an exception — the keep decision must still see it.
        responses = list(core.stream_infer(stream_request("BOGUS")))
        assert any(r.error_message for r in responses)
        records = core.flight.snapshot("repeat_int32")
        assert records and records[-1]["reason"] == "error"
        assert records[-1]["status"] == "INVALID_ARGUMENT"
        # a clean long stream is NOT kept (allow_slow=False)
        kept_before = core.flight.stats()["repeat_int32"]["kept_total"]
        for _ in core.stream_infer(stream_request("IN")):
            pass
        assert core.flight.stats()["repeat_int32"]["kept_total"] == \
            kept_before
    finally:
        core.shutdown()


# -- SLO statistics + metrics over the core -------------------------------


def test_slo_statistics_and_metrics_families(slo_core):
    core = slo_core
    core.slo.min_sample_interval_s = 0.0
    for i in range(8):
        core.infer(_simple_request("simple_slo", i))
    stat = core.model_statistics("simple_slo").model_stats[0]
    assert stat.slo_stats.p99_latency_target_us == 50_000
    assert stat.slo_stats.availability_target == \
        pytest.approx(0.999)
    assert stat.slo_stats.healthy
    text = core.metrics_text()
    for family in ("tpu_slo_target", "tpu_slo_burn_rate",
                   "tpu_slo_budget_remaining", "tpu_slo_healthy",
                   "tpu_server_info"):
        assert family in text, family
    errors, types, _series = lint_exposition(text)
    assert not errors, errors[:5]
    assert types["tpu_slo_burn_rate"] == "gauge"
    assert 'window="fast"' in text and 'window="slow"' in text


def test_server_info_uptime_advances(slo_core):
    core = slo_core
    first = [line for line in core.metrics_text().splitlines()
             if line.startswith("tpu_server_info")][0]
    assert 'name="client_tpu_server"' in first
    assert 'version=' in first
    core._started_mono -= 100  # simulate an older process
    second = [line for line in core.metrics_text().splitlines()
              if line.startswith("tpu_server_info")][0]
    assert int(second.rsplit(" ", 1)[1]) >= \
        int(first.rsplit(" ", 1)[1]) + 100


# -- config rendering round-trip ------------------------------------------


def test_slo_block_config_rendering_round_trip(slo_core):
    core = slo_core
    config = core.model_config("simple_slo").config
    assert config.slo.p99_latency_us == 50_000
    assert config.slo.availability == pytest.approx(0.999)
    # over the embedded REST dispatcher (JSON view)
    status, _headers, body = http_call(
        core, "GET", "/v2/models/simple_slo/config", {}, b"")
    assert status == 200
    doc = json.loads(body)
    assert int(doc["slo"]["p99_latency_us"]) == 50_000
    assert float(doc["slo"]["availability"]) == pytest.approx(0.999)
    # a model without the block renders no slo section
    core.repository.load("simple")
    config = core.model_config("simple").config
    assert not config.HasField("slo")


# -- /v2/debug e2e over the three transports ------------------------------


def _assert_debug_doc(doc):
    assert doc["server"]["name"] == "client_tpu_server"
    assert doc["server"]["uptime_s"] >= 0
    assert any(m["name"] == "simple_slo" for m in doc["models"])
    assert "simple_slo" in doc["slo"]
    assert "in_flight" in doc and "flight" in doc
    assert lint_debug_snapshot(doc) == []


def test_debug_endpoint_http_embed(slo_core):
    core = slo_core
    core.infer(_simple_request("simple_slo"))
    status, _headers, body = http_call(core, "GET",
                                       "/v2/debug?model=simple_slo",
                                       {}, b"")
    assert status == 200
    _assert_debug_doc(json.loads(body))
    status, _headers, body = http_call(
        core, "GET", "/v2/debug/flight?model=simple_slo", {}, b"")
    assert status == 200
    doc = json.loads(body)
    assert "records" in doc and "stats" in doc
    # the native HTTP/1.1 front-end strips the query before routing
    # and forwards it as x-request-query — the filter must still work
    status, _headers, body = http_call(
        core, "GET", "/v2/debug", {"x-request-query": "model=no_such"},
        b"")
    assert status == 200
    assert json.loads(body)["models"] == []  # filter applied


def test_debug_endpoint_aiohttp(slo_core):
    core = slo_core
    chaos.configure_from_spec("latency_ms=120,seed=3")
    core.infer(_simple_request("simple_slo"))
    chaos.configure(None)
    runner = start_http_server_thread(core, host="127.0.0.1", port=0)
    try:
        base = "http://127.0.0.1:%d" % runner.port
        with urllib.request.urlopen(base + "/v2/debug") as response:
            doc = json.loads(response.read())
        _assert_debug_doc(doc)
        url = base + "/v2/debug/flight?model=simple_slo"
        with urllib.request.urlopen(url) as response:
            flight_doc = json.loads(response.read())
        assert any(r["reason"] == "slow"
                   for r in flight_doc["records"])
        assert lint_debug_snapshot(flight_doc) == []
    finally:
        runner.stop()


def test_debug_endpoint_grpc(slo_core):
    import grpc

    core = slo_core
    chaos.configure_from_spec("error_rate=1.0,seed=3")
    with pytest.raises(InferenceServerException):
        core.infer(_simple_request("simple_slo"))
    chaos.configure(None)
    handle = start_grpc_server(core=core, address="127.0.0.1:0")
    try:
        channel = grpc.insecure_channel(handle.address)
        snapshot = channel.unary_unary(
            "/inference.Debug/Snapshot",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        _assert_debug_doc(json.loads(snapshot(b'{"model":"simple_slo"}')))
        flight = channel.unary_unary(
            "/inference.Debug/Flight",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        doc = json.loads(flight(b'{"model":"simple_slo"}'))
        assert any(r["reason"] == "shed" for r in doc["records"])
        channel.close()
    finally:
        handle.stop()


def test_debug_queue_section_shows_bucket_depth():
    core = build_core(["simple_qos"])
    try:
        batcher = core._batcher_for(core.repository.get("simple_qos"))
        snap = batcher.debug_snapshot()
        assert snap["max_queue_size"] == 32
        assert snap["pending_count"] == 0
        core.infer(_simple_request("simple_qos", batched=True))
        doc = core.debug_snapshot("simple_qos")
        assert "simple_qos" in doc["queues"]
        assert lint_debug_snapshot(doc) == []
    finally:
        core.shutdown()


# -- debug-snapshot cardinality lint --------------------------------------


def test_lint_debug_snapshot_flags_identity_keys_and_fanout():
    assert lint_debug_snapshot({"models": {"simple": {"ok": 1}}}) == []
    bad = {"requests": {"a" * 16: {"age": 1}}}  # hex-id keyed dict
    errors = lint_debug_snapshot(bad)
    assert errors and "identity" in errors[0]
    uuid_key = "12345678-1234-1234-1234-123456789abc"
    assert lint_debug_snapshot({"x": {uuid_key: 1}})
    assert lint_debug_snapshot({"x": {"1234567": 1}})
    big = {"x": {str(n) + "k": n for n in range(3000)}}
    errors = lint_debug_snapshot(big)
    assert errors and "fans out" in errors[0]


# -- replica ejection stamps the ring -------------------------------------


def test_breaker_trip_stamps_flight_records():
    core = build_core(["simple_replicas"])
    try:
        model = core.repository.get("simple_replicas")
        # seed the ring with a kept record first
        model.flight_slow_us = 1
        core.infer(_simple_request("simple_replicas", batched=True))
        assert core.flight.snapshot("simple_replicas")
        chaos.configure(chaos.ChaosConfig(error_rate=1.0, seed=3,
                                          replica="simple_replicas:1"))
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                core.infer(_simple_request("simple_replicas",
                                           batched=True))
            except InferenceServerException:
                pass
            snap = core.debug_snapshot("simple_replicas")
            replicas = snap["replicas"].get("simple_replicas", {})
            if replicas.get("ejections", 0) >= 1:
                break
            time.sleep(0.05)
        chaos.configure(None)
        records = core.flight.snapshot("simple_replicas")
        labels = [incident["label"]
                  for record in records
                  for incident in record["incidents"]]
        assert any("replica=1" in label for label in labels), labels
    finally:
        chaos.configure(None)
        core.shutdown()


# -- perf --slo report unit -----------------------------------------------


def test_print_slo_report_verdicts(capsys):
    from client_tpu.perf.metrics_manager import parse_prometheus
    from client_tpu.perf.report import print_slo_report

    text = "\n".join([
        'tpu_slo_target{model="m",objective="p99_latency_us"} 5000.0',
        'tpu_slo_burn_rate{model="m",window="fast"} 2.5',
        'tpu_slo_burn_rate{model="m",window="slow"} 0.2',
        'tpu_slo_budget_remaining{model="m"} 0.8',
        'tpu_slo_healthy{model="m"} 1',
    ])
    metrics = parse_prometheus(text)
    assert print_slo_report(metrics) is True
    assert print_slo_report(metrics, strict=True) is False  # fast > 1
    out = capsys.readouterr().out
    assert "burn fast 2.50x / slow 0.20x" in out
    assert "verdict HEALTHY" in out
    unhealthy = parse_prometheus(text.replace(
        'tpu_slo_healthy{model="m"} 1', 'tpu_slo_healthy{model="m"} 0'))
    assert print_slo_report(unhealthy) is False
    # an explicitly requested gate must not pass vacuously when the
    # scrape carries no tpu_slo_* series at all
    assert print_slo_report(parse_prometheus("")) is False


def test_flight_scratch_traces_never_stamp_exemplars(slo_core):
    """At trace_rate=0 with the flight recorder on, every request
    carries a scratch trace — but its (usually discarded) trace id
    must never land as a telemetry exemplar; only SAMPLED traces
    qualify for the exemplar->span-tree join."""
    core = slo_core
    for i in range(5):
        core.infer(_simple_request("simple_slo", i))
    hist = core.telemetry.for_model("simple_slo").request
    assert hist.snapshot()["exemplars"] == {}
    # sampled traffic DOES stamp exemplars, with the emitted trace id
    core.trace_setting("", {
        "trace_level": ["TIMESTAMPS"], "trace_rate": ["1"],
        "trace_file": ["/tmp/_flight_exemplar_trace.jsonl"],
    })
    core.infer(_simple_request("simple_slo"))
    core.trace_setting("", {"trace_level": ["OFF"]})
    exemplars = hist.snapshot()["exemplars"]
    assert exemplars, "sampled request stamped no exemplar"


def test_availability_burn_counts_each_drop_once(slo_core):
    """A queue reject/shed increments both its per-cause counter AND
    fail_count; the availability collector must count it once (via
    fail_count alone), not twice."""
    core = slo_core
    stats = core._stats_for("simple_slo")
    target = core.slo._targets_fn()[0][1]
    with stats.lock:
        stats.success_count = 999
        stats.fail_count = 1
        stats.rejected_count = 1  # the same dropped request
        stats.shed_count = 1      # (cause counters overlap fail_count)
    sample = core._slo_collect("simple_slo", target)
    assert sample.ok_count == 999.0
    assert sample.bad_count == 1.0
