"""Migration shims: reference-style `tritonclient.*` imports resolve
to client_tpu modules (parity-plus for the reference's deprecation
shims, SURVEY.md §2.2)."""

import sys

import numpy as np
import pytest


@pytest.fixture()
def compat():
    import client_tpu.compat as compat

    with pytest.warns(DeprecationWarning):
        compat.install()
    yield compat
    compat.uninstall()


def test_grpc_alias_is_the_real_client(compat):
    import tritonclient.grpc as grpcclient

    import client_tpu.grpc as real

    assert grpcclient is real
    assert hasattr(grpcclient, "InferenceServerClient")
    assert hasattr(grpcclient, "InferInput")


def test_utils_alias_round_trips_serialization(compat):
    import tritonclient.utils as utils

    tensor = np.array([b"a", b"bc"], dtype=np.object_)
    wire = utils.serialize_byte_tensor(tensor)
    back = utils.deserialize_bytes_tensor(np.asarray(wire).tobytes())
    assert list(back) == [b"a", b"bc"]


def test_cuda_shm_alias_targets_tpu_arena(compat):
    import tritonclient.utils.cuda_shared_memory as cudashm

    import client_tpu.utils.tpu_shared_memory as tpushm

    assert cudashm is tpushm
    # The seven-function CUDA-parity surface resolves through the alias.
    for name in ("create_shared_memory_region", "get_raw_handle",
                 "set_shared_memory_region", "get_contents_as_numpy",
                 "set_shared_memory_region_from_dlpack",
                 "as_shared_memory_tensor", "destroy_shared_memory_region"):
        assert hasattr(cudashm, name), name


def test_attribute_access_through_parent(compat):
    import tritonclient

    assert hasattr(tritonclient, "grpc")
    assert hasattr(tritonclient, "utils")
    assert tritonclient.utils.np_to_triton_dtype(np.int32) == "INT32"


def test_install_is_idempotent_and_uninstall_cleans(compat):
    compat.install()  # second call: no-op, no error
    assert "tritonclient" in sys.modules
    compat.uninstall()
    assert "tritonclient" not in sys.modules
    compat.install(quiet=True)  # reinstall for fixture teardown
