"""Robustness layer tests: RetryPolicy backoff/jitter bounds, the
circuit-breaker state machine, queue-policy admission control +
deadline enforcement in the dynamic batcher, and end-to-end saturation
behavior over HTTP and gRPC (503/UNAVAILABLE + Retry-After, expired
timeouts rejected without executing, drops visible in metrics)."""

import random
import threading
import time

import numpy as np
import pytest

from client_tpu import robust
from client_tpu.robust import CircuitBreaker, RetryPolicy, call_with_retry
from client_tpu.server.batcher import DynamicBatcher
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.utils import InferenceServerException


# -- RetryPolicy ----------------------------------------------------------


def test_backoff_exponential_without_jitter():
    policy = RetryPolicy(initial_backoff_s=0.1, backoff_multiplier=2.0,
                         max_backoff_s=1.0, jitter=False)
    assert policy.backoff_s(0) == pytest.approx(0.1)
    assert policy.backoff_s(1) == pytest.approx(0.2)
    assert policy.backoff_s(2) == pytest.approx(0.4)
    # capped at max_backoff_s
    assert policy.backoff_s(10) == pytest.approx(1.0)


def test_backoff_full_jitter_bounds():
    policy = RetryPolicy(initial_backoff_s=0.05, backoff_multiplier=2.0,
                         max_backoff_s=0.5, rng=random.Random(7))
    for attempt in range(8):
        cap = min(0.05 * 2 ** attempt, 0.5)
        draws = [policy.backoff_s(attempt) for _ in range(50)]
        assert all(0.0 <= d <= cap for d in draws)
        # full jitter actually spreads over the interval
        assert max(draws) > cap * 0.5


def test_retryable_statuses():
    policy = RetryPolicy()
    assert policy.is_retryable(
        InferenceServerException("x", status="UNAVAILABLE"))
    assert policy.is_retryable(InferenceServerException("x", status="503"))
    assert not policy.is_retryable(
        InferenceServerException("x", status="INVALID_ARGUMENT"))
    assert not policy.is_retryable(InferenceServerException("x"))
    assert not policy.is_retryable(ValueError("x"))


def test_call_with_retry_recovers():
    robust.reset_retry_total()
    calls = []

    def flaky(remaining):
        calls.append(remaining)
        if len(calls) < 3:
            raise InferenceServerException("down", status="UNAVAILABLE")
        return "ok"

    policy = RetryPolicy(max_attempts=4, initial_backoff_s=0.001)
    assert call_with_retry(flaky, policy) == "ok"
    assert len(calls) == 3
    assert robust.retry_total() == 2


def test_call_with_retry_exhausts_attempts():
    calls = []

    def always_down(remaining):
        calls.append(1)
        raise InferenceServerException("down", status="UNAVAILABLE")

    policy = RetryPolicy(max_attempts=3, initial_backoff_s=0.001)
    with pytest.raises(InferenceServerException):
        call_with_retry(always_down, policy)
    assert len(calls) == 3


def test_call_with_retry_not_retryable():
    calls = []

    def bad_request(remaining):
        calls.append(1)
        raise InferenceServerException("bad", status="INVALID_ARGUMENT")

    with pytest.raises(InferenceServerException):
        call_with_retry(bad_request, RetryPolicy(max_attempts=5))
    assert len(calls) == 1


def test_call_with_retry_deadline_budget_shrinks():
    """Each attempt sees strictly less remaining budget, and a backoff
    that would overrun the deadline re-raises instead of sleeping."""
    seen = []
    fake_now = [0.0]

    def clock():
        return fake_now[0]

    def sleep(s):
        fake_now[0] += s

    def failing(remaining):
        seen.append(remaining)
        fake_now[0] += 0.1  # each attempt burns 100ms
        raise InferenceServerException("down", status="UNAVAILABLE")

    policy = RetryPolicy(max_attempts=10, initial_backoff_s=0.05,
                         backoff_multiplier=1.0, jitter=False)
    with pytest.raises(InferenceServerException):
        call_with_retry(failing, policy, deadline_s=0.4, sleep=sleep,
                        clock=clock)
    assert len(seen) >= 2
    assert seen == sorted(seen, reverse=True)  # shrinking budget
    assert all(r <= 0.4 for r in seen)
    # never slept past the deadline
    assert fake_now[0] <= 0.4 + 0.1


# -- CircuitBreaker -------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def test_breaker_opens_after_threshold():
    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=3, reset_timeout_s=5.0,
                             clock=clock)
    for _ in range(2):
        breaker.before_call()
        breaker.record_failure()
    assert breaker.state == CircuitBreaker.CLOSED
    breaker.before_call()
    breaker.record_failure()
    assert breaker.state == CircuitBreaker.OPEN
    with pytest.raises(InferenceServerException) as excinfo:
        breaker.before_call()
    assert excinfo.value.status() == "UNAVAILABLE"


def test_breaker_half_open_probe_closes_on_success():
    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                             clock=clock)
    breaker.before_call()
    breaker.record_failure()
    assert breaker.state == CircuitBreaker.OPEN
    clock.now = 6.0
    breaker.before_call()  # admitted as the half-open probe
    assert breaker.state == CircuitBreaker.HALF_OPEN
    # a second caller is shed while the probe is in flight
    with pytest.raises(InferenceServerException):
        breaker.before_call()
    breaker.record_success()
    assert breaker.state == CircuitBreaker.CLOSED
    breaker.before_call()  # closed again: normal traffic


def test_breaker_half_open_probe_reopens_on_failure():
    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                             clock=clock)
    breaker.before_call()
    breaker.record_failure()
    clock.now = 6.0
    breaker.before_call()
    breaker.record_failure()
    assert breaker.state == CircuitBreaker.OPEN
    # the open timer restarted at the probe failure
    clock.now = 10.0
    with pytest.raises(InferenceServerException):
        breaker.before_call()
    clock.now = 11.5
    breaker.before_call()  # next probe window


def test_breaker_ignores_definitive_client_errors():
    """5 bad-request responses must NOT open the circuit — the server
    answering 400 decisively is proof it is healthy."""
    breaker = CircuitBreaker(failure_threshold=3, reset_timeout_s=60.0)

    def bad_request(remaining):
        raise InferenceServerException("bad shape",
                                       status="INVALID_ARGUMENT")

    for _ in range(5):
        with pytest.raises(InferenceServerException):
            call_with_retry(bad_request, None, breaker)
    assert breaker.state == CircuitBreaker.CLOSED
    breaker.before_call()  # healthy traffic still flows


def test_half_open_probe_settles_on_unexpected_exception():
    """A non-InferenceServerException escaping the probe attempt must
    still resolve the half-open state — an unresolved probe would
    lock the client out forever."""
    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                             clock=clock)
    with pytest.raises(InferenceServerException):
        call_with_retry(
            lambda r: (_ for _ in ()).throw(
                InferenceServerException("down", status="UNAVAILABLE")),
            None, breaker)
    assert breaker.state == CircuitBreaker.OPEN
    clock.now = 6.0

    def buggy_probe(remaining):
        raise ValueError("garbled response header")

    with pytest.raises(ValueError):
        call_with_retry(buggy_probe, None, breaker)
    # probe resolved (as a failure) -> open again, NOT wedged half-open
    assert breaker.state == CircuitBreaker.OPEN
    clock.now = 12.0
    breaker.before_call()  # the next probe window still admits a call
    breaker.record_success()
    assert breaker.state == CircuitBreaker.CLOSED


def test_cancellation_is_not_availability_evidence():
    """Caller-side aborts (KeyboardInterrupt, asyncio cancellation)
    must free a probe slot but never open the circuit: the server
    never failed anything."""
    breaker = CircuitBreaker(failure_threshold=2, reset_timeout_s=60.0)

    def impatient(remaining):
        raise KeyboardInterrupt()

    for _ in range(5):
        with pytest.raises(KeyboardInterrupt):
            call_with_retry(impatient, None, breaker)
    assert breaker.state == CircuitBreaker.CLOSED


def test_exhausted_counter_tracks_unrecovered_failures():
    robust.reset_retry_total()
    policy = RetryPolicy(max_attempts=3, initial_backoff_s=0.001)

    def always_down(remaining):
        raise InferenceServerException("down", status="UNAVAILABLE")

    with pytest.raises(InferenceServerException):
        call_with_retry(always_down, policy)
    assert robust.exhausted_total() == 1
    # non-retryable escapes are NOT "unrecovered faults"
    with pytest.raises(InferenceServerException):
        call_with_retry(
            lambda r: (_ for _ in ()).throw(
                InferenceServerException("bad", status="INVALID_ARGUMENT")),
            policy)
    assert robust.exhausted_total() == 1
    # a recovered call does not count
    calls = []

    def flaky(remaining):
        calls.append(1)
        if len(calls) < 2:
            raise InferenceServerException("down", status="UNAVAILABLE")
        return "ok"

    assert call_with_retry(flaky, policy) == "ok"
    assert robust.exhausted_total() == 1
    robust.reset_retry_total()
    assert robust.exhausted_total() == 0


def test_breaker_opening_mid_loop_skips_phantom_retry():
    """When the first failure opens the breaker, the executor must
    raise the ORIGINAL error immediately — no backoff sleep toward an
    attempt the breaker will refuse, no phantom retry count, and the
    failure lands in exhausted_total()."""
    robust.reset_retry_total()
    breaker = CircuitBreaker(failure_threshold=1, reset_timeout_s=60.0)
    slept = []

    def down(remaining):
        raise InferenceServerException("down", status="UNAVAILABLE")

    with pytest.raises(InferenceServerException) as excinfo:
        call_with_retry(down, RetryPolicy(max_attempts=4), breaker,
                        sleep=slept.append)
    assert "down" in str(excinfo.value)  # the real error, not breaker-open
    assert slept == []
    assert robust.retry_total() == 0
    assert robust.exhausted_total() == 1


def test_call_with_retry_respects_open_breaker():
    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=1, reset_timeout_s=60.0,
                             clock=clock)
    breaker.before_call()
    breaker.record_failure()
    calls = []

    def fn(remaining):
        calls.append(1)
        return "ok"

    with pytest.raises(InferenceServerException):
        call_with_retry(fn, RetryPolicy(max_attempts=3), breaker)
    assert calls == []  # failed fast, no network I/O


# -- queue policy in the dynamic batcher ---------------------------------


class GatedModel(ServedModel):
    max_batch_size = 8
    dynamic_batching = True

    def __init__(self):
        super().__init__()
        self.name = "gated"
        self.inputs = [TensorSpec("IN", "FP32", [4])]
        self.outputs = [TensorSpec("OUT", "FP32", [4])]
        self.executions = []
        self.gate = threading.Event()

    def infer(self, inputs, parameters=None):
        self.gate.wait()
        array = np.asarray(inputs["IN"])
        self.executions.append(array.shape[0])
        return {"OUT": array * 2.0}


def _submit(batcher, i, params=None, results=None):
    def run():
        try:
            out, _, _ = batcher.infer(
                {"IN": np.full((1, 4), float(i), np.float32)},
                dict(params or {}), 1)
            results[i] = ("ok", float(out["OUT"][0, 0]))
        except InferenceServerException as e:
            results[i] = (e.status(), str(e))

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    return thread


def test_admission_control_rejects_at_max_queue_size():
    model = GatedModel()
    rejects = []
    batcher = DynamicBatcher(model, max_queue_delay_us=200_000,
                             pipeline_depth=1, max_queue_size=2,
                             reject_hook=lambda: rejects.append(1))
    results = {}
    threads = [_submit(batcher, 0, results=results)]
    time.sleep(0.25)  # first request dispatched, holds the pipeline
    threads += [_submit(batcher, i, results=results) for i in (1, 2)]
    time.sleep(0.25)  # queue now holds max_queue_size requests
    threads += [_submit(batcher, i, results=results) for i in (3, 4)]
    time.sleep(0.25)
    assert results.get(3, (None,))[0] == "UNAVAILABLE"
    assert results.get(4, (None,))[0] == "UNAVAILABLE"
    assert "max_queue_size" in results[3][1]
    model.gate.set()
    for thread in threads:
        thread.join(timeout=10)
    batcher.stop()
    assert len(rejects) == 2
    # admitted requests all completed
    for i in (0, 1, 2):
        assert results[i][0] == "ok"
    assert sum(model.executions) == 3


def test_expired_timeout_rejected_before_dispatch():
    model = GatedModel()
    timeouts = []
    batcher = DynamicBatcher(model, max_queue_delay_us=500_000,
                             pipeline_depth=1,
                             timeout_hook=lambda: timeouts.append(1))
    results = {}
    t0 = _submit(batcher, 0, results=results)
    time.sleep(0.15)  # request 0 occupies the pipeline at the gate
    t1 = _submit(batcher, 1, params={"timeout": 100_000}, results=results)
    deadline = time.monotonic() + 5
    while 1 not in results and time.monotonic() < deadline:
        time.sleep(0.01)
    assert results.get(1, (None,))[0] == "DEADLINE_EXCEEDED"
    model.gate.set()
    t0.join(timeout=10)
    t1.join(timeout=10)
    batcher.stop()
    assert len(timeouts) == 1
    # the expired request NEVER reached the model
    assert sum(model.executions) == 1


def test_default_timeout_and_override_disallowed():
    model = GatedModel()
    batcher = DynamicBatcher(model, max_queue_delay_us=500_000,
                             pipeline_depth=1,
                             default_timeout_us=100_000,
                             allow_timeout_override=False)
    results = {}
    t0 = _submit(batcher, 0, results=results)
    time.sleep(0.15)
    # asks for 10s but overrides are off: the 100ms default applies
    t1 = _submit(batcher, 1, params={"timeout": 10_000_000},
                 results=results)
    deadline = time.monotonic() + 5
    while 1 not in results and time.monotonic() < deadline:
        time.sleep(0.01)
    assert results.get(1, (None,))[0] == "DEADLINE_EXCEEDED"
    model.gate.set()
    t0.join(timeout=10)
    t1.join(timeout=10)
    batcher.stop()


def test_timeout_action_delay_keeps_request():
    model = GatedModel()
    batcher = DynamicBatcher(model, max_queue_delay_us=100_000,
                             pipeline_depth=1,
                             default_timeout_us=50_000,
                             timeout_action="DELAY")
    results = {}
    t0 = _submit(batcher, 0, results=results)
    time.sleep(0.1)
    t1 = _submit(batcher, 1, results=results)
    time.sleep(0.3)  # far past the 50ms deadline
    model.gate.set()
    t0.join(timeout=10)
    t1.join(timeout=10)
    batcher.stop()
    # DELAY: the expired request still executed once capacity freed
    assert results[1][0] == "ok"


def test_differing_timeouts_still_fuse():
    """`timeout` is excluded from the fusion fingerprint: the batcher
    enforces deadlines per request, so mixed-timeout traffic must fuse
    into one execution instead of fragmenting."""
    model = GatedModel()
    batcher = DynamicBatcher(model, max_queue_delay_us=300_000)
    results = {}
    threads = [
        _submit(batcher, i, params={"timeout": 10_000_000 + i * 7},
                results=results)
        for i in range(4)
    ]
    time.sleep(0.2)
    model.gate.set()
    for thread in threads:
        thread.join(timeout=10)
    batcher.stop()
    assert all(results[i][0] == "ok" for i in range(4))
    assert len(model.executions) < 4  # fused despite distinct timeouts


# -- model config renders the queue policy -------------------------------


def test_config_pb_renders_queue_policy():
    class Policied(GatedModel):
        max_queue_size = 16
        default_queue_policy_timeout_us = 250_000
        allow_timeout_override = False
        timeout_action = "DELAY"

    config = Policied().config_pb()
    assert config.dynamic_batching.max_queue_size == 16
    assert config.dynamic_batching.default_queue_policy_timeout_us == 250_000
    assert not config.dynamic_batching.allow_timeout_override
    assert config.dynamic_batching.timeout_action == "DELAY"


# -- HTTP connection pool / error chaining -------------------------------


def test_keepalive_pool_acquire_times_out():
    from client_tpu.http._client import _KeepAliveConnectionPool

    pool = _KeepAliveConnectionPool("127.0.0.1", 59998, size=1, timeout=5.0,
                                    acquire_timeout=0.2)
    conn = pool.acquire()  # only slot, never released (simulated leak)
    assert conn is not None
    start = time.monotonic()
    with pytest.raises(InferenceServerException) as excinfo:
        pool.acquire()
    assert time.monotonic() - start < 2.0  # bounded, not a deadlock
    assert excinfo.value.status() == "UNAVAILABLE"
    assert "leak" in str(excinfo.value)


def test_http_connection_error_preserves_cause():
    import client_tpu.http as httpclient

    with httpclient.InferenceServerClient("127.0.0.1:59997") as client:
        with pytest.raises(InferenceServerException) as excinfo:
            client.is_server_live()
    assert excinfo.value.status() == "UNAVAILABLE"
    assert isinstance(excinfo.value.__cause__, OSError)


def test_grpc_error_preserves_cause():
    import grpc

    import client_tpu.grpc as grpcclient

    with grpcclient.InferenceServerClient("127.0.0.1:59996") as client:
        with pytest.raises(InferenceServerException) as excinfo:
            client.is_server_live(client_timeout=0.5)
    assert isinstance(excinfo.value.__cause__, grpc.RpcError)


# -- end to end: saturation over real transports -------------------------


class SlowBatchModel(ServedModel):
    """Deterministically slow batched model: each execution takes
    ``delay_s`` so a handful of concurrent requests saturates the
    2-deep queue."""

    max_batch_size = 4
    dynamic_batching = True
    pipeline_depth = 1
    max_queue_size = 2
    max_queue_delay_us = 1000

    def __init__(self, delay_s: float = 0.25, name: str = "slow_batch"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("IN", "FP32", [4])]
        self.outputs = [TensorSpec("OUT", "FP32", [4])]
        self._delay = delay_s

    def infer(self, inputs, parameters=None):
        time.sleep(self._delay)
        return {"OUT": np.asarray(inputs["IN"]) * 2.0}


@pytest.fixture()
def saturable_core():
    from client_tpu.server.app import build_core

    core = build_core([])
    core.repository.add_model(SlowBatchModel())
    yield core
    core.shutdown()


def _slow_inputs(client_mod):
    inputs = [client_mod.InferInput("IN", [1, 4], "FP32")]
    inputs[0].set_data_from_numpy(np.ones((1, 4), np.float32))
    return inputs


def _flood(fn, n):
    """Run fn() on n threads; returns (ok_count, statuses, hung)."""
    outcomes = [None] * n

    def run(i):
        try:
            fn()
            outcomes[i] = "ok"
        except InferenceServerException as e:
            outcomes[i] = e.status() or "error"

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    hung = sum(1 for t in threads if t.is_alive())
    ok = sum(1 for o in outcomes if o == "ok")
    return ok, outcomes, hung


def test_http_saturation_returns_503_with_retry_after(saturable_core):
    import urllib.request

    import client_tpu.http as httpclient
    from client_tpu.server.http_server import start_http_server_thread

    runner = start_http_server_thread(saturable_core, host="127.0.0.1",
                                      port=0)
    try:
        with httpclient.InferenceServerClient(
                "127.0.0.1:%d" % runner.port, concurrency=12) as client:
            ok, outcomes, hung = _flood(
                lambda: client.infer("slow_batch", _slow_inputs(httpclient)),
                12)
        assert hung == 0, "requests must never hang under saturation"
        rejected = outcomes.count("503")
        assert rejected > 0, "bounded queue must shed load: %s" % outcomes
        assert ok > 0
        assert ok + rejected == 12
        # Retry-After rides on the 503: keep the queue saturated with
        # looping background workers and probe the raw response
        # headers through the client's transport.
        body, json_len = httpclient.InferenceServerClient.\
            generate_request_body(_slow_inputs(httpclient))
        from client_tpu.protocol.http_wire import HEADER_LEN

        probe_headers = {HEADER_LEN: str(json_len),
                         "Content-Type": "application/octet-stream"}
        path = "/v2/models/slow_batch/infer"
        stop = threading.Event()
        flood_client = httpclient.InferenceServerClient(
            "127.0.0.1:%d" % runner.port, concurrency=12)

        def hammer():
            while not stop.is_set():
                try:
                    flood_client.infer("slow_batch",
                                       _slow_inputs(httpclient))
                except InferenceServerException:
                    pass

        workers = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(8)]
        for worker in workers:
            worker.start()
        probe_client = httpclient.InferenceServerClient(
            "127.0.0.1:%d" % runner.port)
        saw_retry_after = False
        deadline = time.monotonic() + 15
        try:
            while not saw_retry_after and time.monotonic() < deadline:
                status, resp_headers, _ = probe_client._request(
                    "POST", path, body=body, headers=dict(probe_headers))
                if status == 503:
                    # delta-seconds form; since the QoS PR the value is
                    # the server's refill/window estimate, not a flat 1s
                    value = resp_headers.get("retry-after")
                    saw_retry_after = (
                        value is not None and float(value) > 0)
                    break
                time.sleep(0.01)
        finally:
            stop.set()
            for worker in workers:
                worker.join(timeout=30)
            probe_client.close()
            flood_client.close()
        assert saw_retry_after, "503 must carry Retry-After"
        # drops are observable
        metrics = saturable_core.metrics_text()
        assert 'tpu_request_rejected_total{model="slow_batch"' in metrics
        assert "tpu_queue_size" in metrics
    finally:
        runner.stop()


def test_grpc_saturation_unavailable_and_retry_recovers():
    from client_tpu.server.app import build_core, start_grpc_server

    import client_tpu.grpc as grpcclient

    core = build_core([])
    core.repository.add_model(SlowBatchModel(name="slow_batch_grpc"))
    handle = start_grpc_server(core=core, address="127.0.0.1:0")
    try:
        with grpcclient.InferenceServerClient(handle.address) as client:
            ok, outcomes, hung = _flood(
                lambda: client.infer("slow_batch_grpc",
                                     _slow_inputs(grpcclient)), 12)
        assert hung == 0
        assert outcomes.count("UNAVAILABLE") > 0
        assert ok > 0
        # with a retry policy, retries recover >= 90% of the
        # rejections (the ISSUE acceptance bar)
        policy = RetryPolicy(max_attempts=15, initial_backoff_s=0.05,
                             max_backoff_s=0.6,
                             rng=random.Random(17))
        with grpcclient.InferenceServerClient(
                handle.address, retry_policy=policy) as client:
            ok2, outcomes2, hung2 = _flood(
                lambda: client.infer("slow_batch_grpc",
                                     _slow_inputs(grpcclient)), 12)
        assert hung2 == 0
        assert ok2 >= 11, "retries must recover rejections: %s" % outcomes2
        stats = core.model_statistics("slow_batch_grpc")
        assert stats.model_stats[0].reject_count > 0
    finally:
        handle.stop()


def test_grpc_expired_timeout_never_executes():
    from client_tpu.server.app import build_core, start_grpc_server

    import client_tpu.grpc as grpcclient

    core = build_core([])
    model = SlowBatchModel(delay_s=0.4, name="slow_batch_to")
    core.repository.add_model(model)
    handle = start_grpc_server(core=core, address="127.0.0.1:0")
    try:
        with grpcclient.InferenceServerClient(handle.address) as client:
            # fill the pipeline so the next request waits in queue
            bg = threading.Thread(
                target=lambda: client.infer("slow_batch_to",
                                            _slow_inputs(grpcclient)),
                daemon=True)
            bg.start()
            time.sleep(0.1)
            with pytest.raises(InferenceServerException) as excinfo:
                client.infer("slow_batch_to", _slow_inputs(grpcclient),
                             timeout=50_000)  # 50ms queue deadline
            assert excinfo.value.status() == "DEADLINE_EXCEEDED"
            bg.join(timeout=20)
        stats = core.model_statistics("slow_batch_to")
        assert stats.model_stats[0].timeout_count == 1
        assert "tpu_request_timeout_total" in core.metrics_text()
    finally:
        handle.stop()


def test_http_client_timeout_parity(saturable_core):
    """The HTTP sync client's per-call client_timeout= bounds the call
    like the gRPC client's (satellite: constructor-only timeouts are
    not enough)."""
    import client_tpu.http as httpclient
    from client_tpu.server.http_server import start_http_server_thread

    runner = start_http_server_thread(saturable_core, host="127.0.0.1",
                                      port=0)
    try:
        with httpclient.InferenceServerClient(
                "127.0.0.1:%d" % runner.port) as client:
            start = time.monotonic()
            with pytest.raises(InferenceServerException) as excinfo:
                client.infer("slow_batch", _slow_inputs(httpclient),
                             client_timeout=0.1)
            elapsed = time.monotonic() - start
            assert elapsed < 2.0
            assert excinfo.value.status() == "DEADLINE_EXCEEDED"
            # a generous deadline succeeds through the deadline-aware
            # response-read loop (and the pooled connection recovers
            # from the timed-out request before it)
            result = client.infer("slow_batch", _slow_inputs(httpclient),
                                  client_timeout=30.0)
            np.testing.assert_array_equal(
                result.as_numpy("OUT"), np.full((1, 4), 2.0, np.float32))
    finally:
        runner.stop()


def test_health_flips_not_ready_during_drain(saturable_core):
    import urllib.request

    from client_tpu.server.http_server import start_http_server_thread

    runner = start_http_server_thread(saturable_core, host="127.0.0.1",
                                      port=0)
    try:
        url = "http://127.0.0.1:%d/v2/health/ready" % runner.port
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert resp.status == 200
        saturable_core.shutdown()  # drain begins: LBs must stop routing
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(url, timeout=5)
        assert excinfo.value.code == 400
        # live stays up (the process exists) while ready is down
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/v2/health/live" % runner.port,
                timeout=5) as resp:
            assert resp.status == 200
    finally:
        runner.stop()
