"""Regression tests for the real defects tpulint's checkers surfaced
in this PR (see docs/static_analysis.md for the checker catalog and
CHANGES.md for the fix list). Each test names its checker id."""

import json

import numpy as np
import pytest

from client_tpu.utils import InferenceServerException


class _StubModel:
    name = "stub"
    version = "1"

    def __init__(self, boom_on_unload=False):
        self.boom_on_unload = boom_on_unload
        self.unloaded = 0

    def warmup(self):
        pass

    def unload(self):
        self.unloaded += 1
        if self.boom_on_unload:
            raise RuntimeError("teardown bug")


# -- resource-pairing: repository.finish_unload listener ordering -----------

def test_unload_listeners_fire_even_when_model_teardown_raises():
    """[resource-pairing] finish_unload ran its unload listeners AFTER
    model.unload() with no finally: a teardown exception skipped cache
    invalidation, so a reloaded instance could serve the crashed
    instance's cached bytes."""
    from client_tpu.server.repository import ModelRepository

    repo = ModelRepository()
    fired = []
    repo.add_unload_listener(fired.append)
    model = _StubModel(boom_on_unload=True)
    repo.add_model(model)
    repo.begin_unload("stub")
    with pytest.raises(RuntimeError):
        repo.finish_unload("stub")
    assert model.unloaded == 1
    assert fired == ["stub"]  # the listener fired despite the raise


# -- resource-pairing: core.unload_model drain state ------------------------

def test_unload_model_completes_drain_when_scheduler_stop_raises():
    """[resource-pairing] core.unload_model called begin_unload, then
    stopped schedulers, then finish_unload — with no finally. A
    scheduler stop() exception left the model UNAVAILABLE 'draining'
    forever, shedding every request with 503 while the instance and
    its device memory stayed resident."""
    from client_tpu.server.app import build_core

    core = build_core(["simple"])
    try:
        class _BoomSequencer:
            def stop(self):
                raise RuntimeError("scheduler stop bug")

        core._sequencers["simple"] = _BoomSequencer()
        with pytest.raises(RuntimeError):
            core.unload_model("simple")
        # finish_unload still ran: the instance is gone (drain state
        # resolved), not stuck draining...
        index = {m.name: m for m in core.repository_index().models}
        assert "unloading" not in index["simple"].reason
        # ...and the model is reloadable + serves again.
        core.load_model("simple")
        assert core.model_ready("simple", "")
    finally:
        core.shutdown()


# -- lock-discipline: arena upload under the region lock --------------------

def test_arena_multi_segment_view_uploads_outside_region_lock():
    """[lock-discipline] as_typed_array's multi-segment path ran
    jax.device_put while holding region.lock — a host->device
    transfer stalling behind the device queue blocked every
    concurrent reader/writer of the region for its duration."""
    from client_tpu.server.tpu_arena import TpuArena

    arena = TpuArena()
    handle = arena.create_region(64, 0)
    region_id = json.loads(handle)["region_id"]
    # Two adjacent RAW segments: the INT32 view over both must take
    # the multi-segment assemble-then-upload path.
    arena.write(region_id, 0, np.arange(4, dtype=np.int32).tobytes())
    arena.write(region_id, 16, np.arange(4, 8, dtype=np.int32).tobytes())
    region = arena._get(region_id)
    real_jax = arena._jax
    observed = {}

    class _JaxProxy:
        def __getattr__(self, name):
            return getattr(real_jax, name)

        @staticmethod
        def device_put(*args, **kwargs):
            observed["lock_held"] = region.lock.locked()
            return real_jax.device_put(*args, **kwargs)

    arena._jax = _JaxProxy()
    try:
        view = np.asarray(
            arena.as_typed_array(region_id, 0, 32, "INT32", [8]))
    finally:
        arena._jax = real_jax
    np.testing.assert_array_equal(view, np.arange(8, dtype=np.int32))
    assert observed == {"lock_held": False}


# -- retry-after: honest estimates on shed paths ----------------------------

def test_draining_model_rejects_with_honest_retry_after():
    """[retry-after] repository.acquire shed draining-model requests
    with a bare UNAVAILABLE; the front-ends then sent the meaningless
    legacy Retry-After '1'. The error now carries the drain-derived
    estimate, end to end through the REST error path."""
    from client_tpu.server.http_embed import _error_reply
    from client_tpu.server.repository import ModelRepository

    repo = ModelRepository()
    repo.add_model(_StubModel())
    repo.begin_unload("stub")
    with pytest.raises(InferenceServerException) as exc_info:
        repo.acquire("stub")
    error = exc_info.value
    assert error.status() == "UNAVAILABLE"
    expected = ModelRepository.DRAIN_TIMEOUT_S / 5.0
    assert error.retry_after_s == pytest.approx(expected)
    status, headers, _body = _error_reply(error)
    assert status == 503
    assert headers["Retry-After"] == "2"  # ceil(expected) seconds


def test_replica_errors_carry_recovery_derived_retry_after():
    """[retry-after] a fully-ejected ReplicaSet rejected with a bare
    UNAVAILABLE; it now advertises the supervisor's recovery interval
    (the honest earliest point a canary can readmit a replica)."""
    from client_tpu.server import replicas as replicas_mod

    model = type("_M", (), {
        "name": "m", "version": "1",
        "instance_group_count": 2,
        "replica_recovery_s": 3.0,
    })()
    replica_set = replicas_mod.ReplicaSet(model)
    try:
        for replica in replica_set.replicas:
            replica.hung = True  # watchdog verdict: domain ejected
        with pytest.raises(InferenceServerException) as exc_info:
            replica_set._pick()
        assert exc_info.value.status() == "UNAVAILABLE"
        assert exc_info.value.retry_after_s == pytest.approx(3.0)
    finally:
        replica_set.stop()
