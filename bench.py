#!/usr/bin/env python
"""Round benchmark — the north-star config (BASELINE.json): ResNet-50
served over gRPC with TPU shared-memory I/O (batch 8, async,
concurrency sweep via the perf harness), client+server co-located.

Prints exactly ONE JSON line. ``vs_baseline`` compares against the
only ResNet-50 throughput the reference publishes (165.8 infer/sec,
TF-Serving GRPC batch 1, docs/benchmarking.md:121 — illustrative, not
hardware-matched; the reference publishes no CUDA-shm number).
"""

import json
import sys


def main():
    sys.path.insert(0, ".")
    from client_tpu.perf.client_backend import (
        BackendKind,
        ClientBackendFactory,
    )
    from client_tpu.perf.data_loader import DataLoader
    from client_tpu.perf.load_manager import (
        ConcurrencyManager,
        InferDataManager,
    )
    from client_tpu.perf.model_parser import ModelParser
    from client_tpu.perf.profiler import InferenceProfiler, MeasurementConfig
    from client_tpu.server.app import build_core, start_grpc_server

    baseline = 165.8  # reference resnet50 TF-Serving GRPC (batch 1)
    batch = 8

    core = build_core(["resnet50"])
    handle = start_grpc_server(core=core)
    try:
        factory = ClientBackendFactory(BackendKind.TRITON_GRPC,
                                       url=handle.address)
        setup_backend = factory.create()
        model = ModelParser().parse(setup_backend, "resnet50",
                                    batch_size=batch)
        loader = DataLoader(model)
        loader.generate_data()
        data_manager = InferDataManager(
            model, loader, shared_memory="tpu",
            output_shm_size=batch * 1000 * 4 + 1024,
            tpu_arena_url=handle.address, batch_size=batch,
        )
        manager = ConcurrencyManager(
            factory=factory, model=model, data_loader=loader,
            data_manager=data_manager, async_mode=True, max_threads=8,
        )
        manager.init()
        config = MeasurementConfig(
            measurement_interval_ms=4000, max_trials=6,
            stability_threshold=0.15,
        )
        profiler = InferenceProfiler(manager, config, setup_backend,
                                     "resnet50")
        # warm the compiled path before measuring
        manager.change_concurrency_level(1)
        import time

        time.sleep(8)
        results = profiler.profile_concurrency_range(4, 4)
        manager.cleanup()
        setup_backend.close()
    finally:
        handle.stop()

    status = results[-1]
    print(json.dumps({
        "metric": "resnet50_tpu_shm_grpc_batch8_c4_infer_per_sec",
        "value": round(status.throughput, 2),
        "unit": "infer/sec",
        "vs_baseline": round(status.throughput / baseline, 4),
        "p50_latency_us": round(status.latency_percentiles.get(50, 0), 1),
        "batch": batch,
    }))


if __name__ == "__main__":
    main()
