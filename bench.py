#!/usr/bin/env python
"""Round benchmark orchestrator.

Never imports jax itself: all JAX/TPU work happens in a child process
(`client_tpu.perf.bench_child`) run under hard wall-clock deadlines, so
a slow TPU-platform initialization can never leave the driver with no
number at all.  Staged degradation:

  attempt 1: child on the image's default platform (TPU on the driver)
             — killed if jax init misses its deadline;
  attempt 2: child forced onto CPU — init is seconds, a number on CPU
             beats a timeout with nothing.

The child measures (budget permitting) `simple` over gRPC, `simple`
in-process (the RPC-tax comparison, analogue of the reference's C-API
mode — reference docs/benchmarking.md:75), then the headline resnet50
batch-8 gRPC + TPU-shared-memory config (BASELINE.json north star),
writing a cumulative result file after every stage.  This process
prints exactly ONE JSON line: the best headline available plus every
stage's numbers.

``vs_baseline`` compares against the only matching throughput the
reference publishes (resnet50: 165.8 infer/sec TF-Serving GRPC batch 1,
docs/benchmarking.md:121; simple: 1407.84 infer/sec HTTP sync,
docs/quick_start.md:94 — illustrative, not hardware-matched).
"""

import json
import os
import pathlib
import signal
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

# jax-free by design (module-level jax imports are checked off in
# client_tpu.perf's import chain): one shared perf_analyzer runner so
# the orchestrator and the child cannot drift on command assembly or
# CSV parsing.
from client_tpu.perf.harness_proc import run_native  # noqa: E402


def log(msg: str) -> None:
    print("[bench %7.1fs] %s" % (time.time() - T0, msg), file=sys.stderr,
          flush=True)


T0 = time.time()


def run_child(platform: str, init_deadline_s: float, deadline_ts: float,
              skip_stages=None):
    """Run one bench child; returns the parsed result dict or None."""
    out = pathlib.Path("/tmp/bench_result.json")
    marker = pathlib.Path("/tmp/bench_init_marker.json")
    for p in (out, marker):
        if p.exists():
            p.unlink()
    cmd = [sys.executable, "-m", "client_tpu.perf.bench_child",
           "--out", str(out), "--init-marker", str(marker),
           "--deadline-ts", str(deadline_ts)]
    if skip_stages:
        cmd += ["--skip-stages", ",".join(skip_stages)]
    env = dict(os.environ)
    if platform:
        cmd += ["--platform", platform]
        if platform == "cpu":
            # The image's sitecustomize force-registers the axon TPU
            # platform; both knobs must be set before the interpreter
            # starts for the child to come up CPU-only.
            env["JAX_PLATFORMS"] = "cpu"
            env["PALLAS_AXON_POOL_IPS"] = ""
    log("spawning child (platform=%s, init deadline %.0fs, total %.0fs)"
        % (platform or "default", init_deadline_s, deadline_ts - time.time()))
    child = subprocess.Popen(cmd, cwd=str(REPO), stdout=sys.stderr,
                             stderr=sys.stderr, env=env)
    init_by = min(time.time() + init_deadline_s, deadline_ts)
    try:
        while child.poll() is None and not marker.exists():
            if time.time() > init_by:
                log("child missed init deadline — killing")
                child.kill()
                child.wait()
                return None
            time.sleep(1)
        # Initialized (or exited); wait for completion until the final
        # deadline, then SIGINT (child flushes partials) and reap.
        while child.poll() is None and time.time() < deadline_ts:
            time.sleep(1)
        if child.poll() is None:
            log("deadline reached — SIGINT to child")
            child.send_signal(signal.SIGINT)
            try:
                child.wait(timeout=20)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait()
    finally:
        if child.poll() is None:
            child.kill()
            child.wait()
    if out.exists():
        try:
            return json.loads(out.read_text())
        except ValueError:
            log("result file unparseable")
    return None


def build_native_harness(deadline_s: float) -> bool:
    """Builds native/build/perf_analyzer so the bench fights with the
    C++ harness. Returns True when the binary is present afterwards.
    Failures are loud: a silent fallback to the Python harness cost
    round 2 its headline."""
    binary = REPO / "native" / "build" / "perf_analyzer"
    built = False
    build_by = time.time() + deadline_s  # one cap across both steps
    try:
        for step in (
            ["cmake", "-S", str(REPO / "native"),
             "-B", str(REPO / "native" / "build"), "-G", "Ninja"],
            ["cmake", "--build", str(REPO / "native" / "build"),
             "--target", "perf_analyzer"],
        ):
            proc = subprocess.run(step, capture_output=True, text=True,
                                  timeout=max(10.0, build_by - time.time()))
            if proc.returncode != 0:
                log("NATIVE BUILD FAILED (%s):\n%s"
                    % (" ".join(step[:2]), proc.stderr[-2000:]))
                break
        else:
            built = binary.exists()
    except (subprocess.SubprocessError, OSError) as exc:
        log("NATIVE BUILD ERROR: %s" % exc)
    if built:
        # Best-effort extras: tpu_serverd (native serving front-end)
        # gates only its own bench stage, never the harness.
        try:
            proc = subprocess.run(
                ["cmake", "--build", str(REPO / "native" / "build"),
                 "--target", "tpu_serverd"],
                capture_output=True, text=True,
                timeout=max(10.0, build_by - time.time()))
            if proc.returncode != 0:
                log("tpu_serverd build failed (stage will be skipped):\n%s"
                    % proc.stderr[-1000:])
        except (subprocess.SubprocessError, OSError) as exc:
            log("tpu_serverd build error (stage will be skipped): %s" % exc)
    if not built and binary.exists():
        # A stale binary from an earlier build would silently bench
        # outdated code — quarantine it so the child falls back to the
        # Python harness LOUDLY rather than misleadingly.
        log("quarantining STALE native harness (build failed)")
        binary.rename(binary.with_suffix(".stale"))
    log("native harness %s"
        % ("ready: %s" % binary if built else
           "UNAVAILABLE — python harness fallback"))
    return built


def as_cpu_fallback(stage: dict) -> dict:
    """Strip TPU-anchored comparison fields from a CPU-measured stage:
    a CPU number against a TPU/reference baseline is apples-to-oranges."""
    return {k: v for k, v in stage.items()
            if not k.startswith(("vs_", "baseline_"))
            and "mfu" not in k
            and not k.endswith("_device")
            and "relay_fetch" not in k
            and k != "itl_p99_improvement"}


# Stages whose model is host-placed (numpy `simple`): their measurement
# is identical on every jax platform, and their vs_baseline anchors the
# reference's own published host-side rows — a CPU-platform run of
# these is NOT degraded data, so they keep their names and anchors.
HOST_PLACED_STAGES = frozenset({
    "simple_grpc", "simple_inprocess", "simple_grpc_native_server",
    "simple_http_native_server_c1", "simple_inprocess_native",
})


def merge_cpu_stages(result: dict, cpu_stages: dict) -> None:
    """Fold CPU-measured stages into `result`: device-bound stages under
    `_cpu_fallback` names with TPU anchors stripped, host-placed stages
    untouched. Never overwrites a stage measured on the real platform."""
    for name, stage in (cpu_stages or {}).items():
        if name in result["stages"]:
            continue
        if name in HOST_PLACED_STAGES:
            result["stages"][name] = stage
        else:
            result["stages"][name + "_cpu_fallback"] = as_cpu_fallback(stage)


def tpu_stages_missing(result: dict) -> list:
    """Model-bound stage names absent from a TPU-labeled run (wedge or
    budget casualties) — the set a relay-recovery retry should target."""
    want = ("resnet50_tpu_shm_grpc", "resnet50_inprocess",
            "bert_grpc_sysshm", "ensemble_stream_grpc",
            "llm_generate_stream")
    have = set(result.get("stages", {}))
    return [name for name in want if name not in have]


def run_native_serving_supplement(result: dict, deadline_ts: float) -> None:
    """Measure the BASELINE.md model configs over the native
    tpu_serverd front-end (own HTTP/2 + gRPC transport around the
    embedded core). Runs after the child process exits — the
    single-client relay allows one device-holding process at a time.
    The Python-front-end stages stay for cross-round comparability;
    these stages are the framework's serving ceiling and the resnet
    one takes the headline when present (measured ~4x the Python
    front-end: the transport, not the device, bounds the Python
    path)."""
    build = REPO / "native" / "build"
    serverd = build / "tpu_serverd"
    analyzer = build / "perf_analyzer"
    if not (serverd.exists() and analyzer.exists()):
        return
    port = 18200 + os.getpid() % 1000
    log_path = pathlib.Path("/tmp/bench_serverd.log")
    env = dict(os.environ, TPUCLIENT_REPO_ROOT=str(REPO))
    # resnet50 ONLY: measured head to head, the embedded-dispatch
    # front-end wins big for unary + arena I/O (resnet 3-4x) but
    # loses for high-concurrency sysshm/streaming configs (bert c64
    # measured 117 vs 574 infer/s, ensemble warm timed out), and
    # co-loading the other models' warmup degraded the resnet stage
    # itself. Those configs keep the Python front-end as their best
    # serving path.
    log("native serving supplement: starting tpu_serverd (resnet50)...")
    with log_path.open("w") as log_file:
        proc = subprocess.Popen(
            [str(serverd), "--host", "127.0.0.1", "--port", str(port),
             "--models", "resnet50"],
            stdout=log_file, stderr=subprocess.STDOUT, env=env)

    def one_stage(stage_name, model, *, batch, concurrency, shm,
                  output_shm, trials, anchor, anchor_src):
        # The warm + measured passes share what budget remains; each
        # pass is clamped so the supplement can never overrun the
        # driver's hard kill (which would lose the whole JSON line).
        addr = "127.0.0.1:%d" % port

        def budget_left():
            return deadline_ts - time.time() - 30
        if budget_left() < 90:
            log("%s skipped: budget" % stage_name)
            return
        try:
            run_native(analyzer, addr, model, batch, concurrency,
                       shm, output_shm, warm=True,
                       timeout=min(240.0, budget_left()))
            if budget_left() < 45:
                log("%s skipped after warm: budget" % stage_name)
                return
            tput, p50 = run_native(
                analyzer, addr, model, batch, concurrency, shm,
                output_shm, window_ms=3000, trials=trials, stability=25,
                timeout=budget_left())
        except (RuntimeError, subprocess.TimeoutExpired, OSError,
                ValueError) as exc:
            log("%s failed (continuing): %s" % (stage_name, exc))
            return
        stage = {
            "batch": batch, "concurrency": concurrency,
            "throughput": tput, "p50_latency_us": p50,
            "vs_baseline": round(tput / anchor, 4),
            "baseline_src": anchor_src,
        }
        # Same chip + model as the child's stage: its device probe
        # carries over, and served-throughput MFU scales linearly with
        # throughput (mfu_est = tput * flops_per_infer / peak).
        child = result["stages"].get("resnet50_tpu_shm_grpc", {})
        for key in ("model_exec_ms_device", "mfu_device",
                    "relay_fetch_ms_est"):
            if key in child:
                stage[key] = child[key]
        if child.get("mfu_est") and child.get("throughput"):
            stage["mfu_est"] = round(
                child["mfu_est"] * tput / child["throughput"], 5)
        result["stages"][stage_name] = stage
        log("stage %s: %.2f infer/sec, p50 %.0f us"
            % (stage_name, tput, p50))

    try:
        listen_deadline = min(deadline_ts - 120, time.time() + 420)
        while time.time() < listen_deadline:
            if proc.poll() is not None:
                log("tpu_serverd exited rc=%s during init" % proc.returncode)
                return
            if "LISTENING" in log_path.read_text():
                break
            time.sleep(2)
        else:
            log("tpu_serverd never listened — skipping supplement")
            return
        # Anchors: resnet vs the reference's published row; the rest vs
        # the r03 regenerated baselines (BASELINE.md — the reference
        # publishes nothing for those shapes).
        one_stage("resnet50_tpu_shm_native_server", "resnet50",
                  batch=8, concurrency=4, shm="tpu", output_shm=33024,
                  trials=5, anchor=165.8,
                  anchor_src="ref resnet50 TF-Serving GRPC row "
                             "(benchmarking.md:121)")
    except (OSError, ValueError) as exc:
        log("native serving supplement failed (continuing): %s" % exc)
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()


def main() -> None:
    os.chdir(REPO)
    # Round-1 evidence: the driver let bench.py run >=25 min before
    # rc=124, and TPU ('axon') platform init alone can take ~10+ min.
    # 25 min total leaves the TPU attempt a real init window while
    # keeping the CPU fallback (needs ~5 min) reachable.
    budget = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    deadline_ts = T0 + budget - 30  # leave margin for this process

    build_native_harness(deadline_s=min(300.0, budget * 0.2))

    # Attempt 1: default platform (TPU on the driver). Give init at
    # most 60% of budget; TPU platform bring-up on this image can be
    # minutes.
    result = run_child("", init_deadline_s=budget * 0.6,
                       deadline_ts=deadline_ts)
    if result is not None and result.get("stages") \
            and result.get("platform") != "tpu":
        # The "default platform" attempt itself came up on CPU (axon
        # never registered — a driver box, or a relay env failure with
        # no wedge). Same honesty contract as the explicit fallback:
        # suffix everything, strip TPU anchors.
        log("attempt 1 ran on %s — labeling all stages cpu_fallback"
            % result.get("platform"))
        relabeled = dict(result, stages={})
        merge_cpu_stages(relabeled, result["stages"])
        result = relabeled
    if (result is None or not result.get("stages")) \
            and deadline_ts - time.time() > 120:
        # Whole-run fallback: every stage below was measured on CPU, so
        # every stage gets the `_cpu_fallback` suffix and loses its
        # TPU-anchored comparison fields — same contract as the
        # partial-supplement path (the r04 record violated this).
        log("falling back to CPU platform")
        cpu_result = run_child("cpu", init_deadline_s=120.0,
                               deadline_ts=deadline_ts)
        if cpu_result is not None and cpu_result.get("stages"):
            result = dict(cpu_result, stages={})
            merge_cpu_stages(result, cpu_result["stages"])
        # The relay wedge is transient (r04 wedged mid-round, r03
        # succeeded end-of-round): with budget left, give TPU one more
        # shot under a short init deadline. Real-platform stages merge
        # in under their true names and outrank the CPU fallbacks.
        if deadline_ts - time.time() > 300:
            log("retrying TPU after CPU fallback (short init deadline)")
            retry = run_child("", init_deadline_s=180.0,
                              deadline_ts=deadline_ts)
            if retry is not None and retry.get("platform") == "tpu" \
                    and retry.get("stages"):
                if result is not None and result.get("stages"):
                    merged = dict(retry)
                    merged["stages"] = dict(result["stages"])
                    merged["stages"].update(retry["stages"])
                    result = merged
                else:
                    result = retry
    elif (result is not None
          and str(result.get("device_probe", "")).startswith("stalled")
          and tpu_stages_missing(result)
          and deadline_ts - time.time() > 180):
        # Relay wedged mid-run: the TPU attempt measured only the
        # host-placed stages. First retry the missing model-bound
        # stages on TPU (the wedge is transient), then supplement
        # whatever still lacks a number on CPU under *_cpu_fallback
        # names — visible data, never the headline.
        if deadline_ts - time.time() > 420:
            log("TPU relay wedged — retrying model stages on TPU")
            retry = run_child("", init_deadline_s=180.0,
                              deadline_ts=deadline_ts - 240,
                              skip_stages=sorted(result["stages"]))
            if retry is not None and retry.get("platform") == "tpu":
                for name, stage in (retry.get("stages") or {}).items():
                    result["stages"].setdefault(name, stage)
                if not str(retry.get("device_probe", "")
                           ).startswith("stalled"):
                    result["device_probe"] = "stalled-then-recovered"
        if tpu_stages_missing(result) and deadline_ts - time.time() > 180:
            log("supplementing still-missing model stages on CPU")
            cpu_result = run_child("cpu", init_deadline_s=120.0,
                                   deadline_ts=deadline_ts,
                                   skip_stages=sorted(result["stages"]))
            merge_cpu_stages(result, (cpu_result or {}).get("stages") or {})
    if result is None or not result.get("stages"):
        print(json.dumps({"metric": "bench_failed", "value": 0,
                          "unit": "infer/sec", "vs_baseline": 0}))
        sys.exit(1)

    # Native-front-end serving phase: only once the chip is known good
    # (a TPU-measured resnet stage exists) and the child — the prior
    # holder of the single-client relay — has exited.
    if (result.get("platform") == "tpu"
            and "resnet50_tpu_shm_grpc" in result["stages"]
            and deadline_ts - time.time() > 240):
        run_native_serving_supplement(result, deadline_ts)

    stages = result["stages"]
    # Headline eligibility: CPU-fallback numbers must never headline
    # under a TPU stage name (apples-to-oranges vs_baseline) — applies
    # to the priority list AND the last-resort pick below.
    eligible = {
        name: stage for name, stage in stages.items()
        if not name.endswith("_cpu_fallback")
        and not (name in ("resnet50_tpu_shm_grpc",
                          "resnet50_tpu_shm_native_server")
                 and result.get("platform") != "tpu")
    }
    if not eligible:
        # Nothing headline-worthy measured: report the first stage
        # under an explicit cpu-fallback name with no TPU-anchored
        # comparison, never a TPU metric name.
        head_key, head = next(iter(stages.items()))
        head = as_cpu_fallback(head)
        if not head_key.endswith("_cpu_fallback"):
            head_key += "_cpu_fallback"
        eligible = {head_key: head}
    for head_key, head_name in (
        ("resnet50_tpu_shm_native_server",
         "resnet50_tpu_shm_native_batch8_c4_infer_per_sec"),
        ("resnet50_tpu_shm_grpc",
         "resnet50_tpu_shm_grpc_batch8_c4_infer_per_sec"),
        ("simple_grpc_native_server",
         "simple_grpc_native_server_c4_infer_per_sec"),
        ("simple_grpc", "simple_grpc_c4_infer_per_sec"),
    ):
        if head_key in eligible:
            head = eligible[head_key]
            break
    else:
        head_key, head = next(iter(eligible.items()))
        head_name = head_key + "_infer_per_sec"
    line = {
        "metric": head_name,
        "value": head["throughput"],
        "unit": "infer/sec",
        "vs_baseline": head.get("vs_baseline", 0),
        "p50_latency_us": head["p50_latency_us"],
        "platform": result.get("platform"),
        "harness": result.get("harness"),
        "stages": stages,
        "wall_s": round(time.time() - T0, 1),
    }
    print(json.dumps(line))


if __name__ == "__main__":
    main()
