#!/usr/bin/env python
"""Round benchmark: end-to-end gRPC infer/sec against the in-repo
server on the `simple` add/sub model, concurrency 1 — the same
methodology as the reference's quick-start measurement
(perf_analyzer docs: 1407.84 infer/sec on an unspecified GPU box,
BASELINE.md). Prints exactly one JSON line.
"""

import json
import sys
import time


def main():
    sys.path.insert(0, ".")
    import numpy as np

    import client_tpu.grpc as grpcclient
    from client_tpu.server.app import start_grpc_server

    baseline = 1407.84  # reference quick_start.md HTTP sync concurrency=1

    handle = start_grpc_server(load_models=["simple"])
    try:
        with grpcclient.InferenceServerClient(handle.address) as client:
            in0 = np.arange(16, dtype=np.int32)
            in1 = np.ones(16, dtype=np.int32)
            inputs = [
                grpcclient.InferInput("INPUT0", [16], "INT32"),
                grpcclient.InferInput("INPUT1", [16], "INT32"),
            ]
            inputs[0].set_data_from_numpy(in0)
            inputs[1].set_data_from_numpy(in1)

            # warmup
            for _ in range(50):
                client.infer("simple", inputs)

            # measure: 3 windows of 2s, report the best (stability-lite)
            best = 0.0
            for _ in range(3):
                count = 0
                start = time.perf_counter()
                while time.perf_counter() - start < 2.0:
                    client.infer("simple", inputs)
                    count += 1
                elapsed = time.perf_counter() - start
                best = max(best, count / elapsed)
    finally:
        handle.stop()

    print(json.dumps({
        "metric": "grpc_sync_infer_per_sec_simple_c1",
        "value": round(best, 2),
        "unit": "infer/sec",
        "vs_baseline": round(best / baseline, 4),
    }))


if __name__ == "__main__":
    main()
