#!/usr/bin/env python
"""Round benchmark — the north-star config (BASELINE.json): ResNet-50
served over gRPC with TPU shared-memory I/O (batch 8, async,
concurrency 4), client+server co-located.

Prefers the native C++ perf_analyzer (the reference's harness is C++;
ours measures with the same client stack users would deploy), falling
back to the Python harness when the native build is unavailable.

Prints exactly ONE JSON line. ``vs_baseline`` compares against the
only ResNet-50 throughput the reference publishes (165.8 infer/sec,
TF-Serving GRPC batch 1, docs/benchmarking.md:121 — illustrative, not
hardware-matched; the reference publishes no CUDA-shm number).
"""

import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent
BASELINE = 165.8  # reference resnet50 TF-Serving GRPC (batch 1)
BATCH = 8
CONCURRENCY = 4


def build_native() -> pathlib.Path:
    """Returns the perf_analyzer binary path, building it if needed."""
    build = REPO / "native" / "build"
    binary = build / "perf_analyzer"
    if binary.exists():
        return binary
    subprocess.run(
        ["cmake", "-S", str(REPO / "native"), "-B", str(build), "-G",
         "Ninja"],
        check=True, capture_output=True, timeout=300,
    )
    subprocess.run(
        ["ninja", "-C", str(build), "perf_analyzer"],
        check=True, capture_output=True, timeout=600,
    )
    return binary


def run_native(binary: pathlib.Path, address: str):
    """One stable concurrency-4 measurement via the C++ harness;
    returns (throughput, p50_us)."""
    export = "/tmp/bench_profile.json"
    csv = "/tmp/bench_latency.csv"
    proc = subprocess.run(
        [str(binary), "-m", "resnet50", "-u", address,
         "-b", str(BATCH), "--shared-memory", "tpu",
         "--output-shared-memory-size", str(BATCH * 1000 * 4 + 1024),
         "--concurrency-range", str(CONCURRENCY),
         "-p", "4000", "-r", "6", "-s", "15",
         "-f", csv, "--profile-export-file", export],
        capture_output=True, text=True, timeout=600,
    )
    if proc.returncode != 0:
        raise RuntimeError("perf_analyzer failed: %s" % proc.stderr[-500:])
    with open(csv) as f:
        f.readline()  # header
        row = f.readline().strip().split(",")
    throughput = float(row[1])
    p50_us = float(row[2])
    return throughput, p50_us


def run_python_harness(handle):
    from client_tpu.perf.client_backend import (
        BackendKind,
        ClientBackendFactory,
    )
    from client_tpu.perf.data_loader import DataLoader
    from client_tpu.perf.load_manager import (
        ConcurrencyManager,
        InferDataManager,
    )
    from client_tpu.perf.model_parser import ModelParser
    from client_tpu.perf.profiler import InferenceProfiler, MeasurementConfig

    factory = ClientBackendFactory(BackendKind.TRITON_GRPC,
                                   url=handle.address)
    setup_backend = factory.create()
    model = ModelParser().parse(setup_backend, "resnet50",
                                batch_size=BATCH)
    loader = DataLoader(model)
    loader.generate_data()
    data_manager = InferDataManager(
        model, loader, shared_memory="tpu",
        output_shm_size=BATCH * 1000 * 4 + 1024,
        tpu_arena_url=handle.address, batch_size=BATCH,
    )
    manager = ConcurrencyManager(
        factory=factory, model=model, data_loader=loader,
        data_manager=data_manager, async_mode=True, max_threads=8,
    )
    manager.init()
    config = MeasurementConfig(
        measurement_interval_ms=4000, max_trials=6,
        stability_threshold=0.15,
    )
    profiler = InferenceProfiler(manager, config, setup_backend, "resnet50")
    manager.change_concurrency_level(1)
    time.sleep(8)  # warm the compiled path before measuring
    results = profiler.profile_concurrency_range(CONCURRENCY, CONCURRENCY)
    manager.cleanup()
    setup_backend.close()
    status = results[-1]
    return status.throughput, status.latency_percentiles.get(50, 0)


def main():
    sys.path.insert(0, str(REPO))
    os.chdir(REPO)
    from client_tpu.server.app import build_core, start_grpc_server

    core = build_core(["resnet50"])
    handle = start_grpc_server(core=core)
    harness = "native"
    try:
        try:
            binary = build_native()
            # Stability trials absorb warm-up; one invocation measures.
            throughput, p50_us = run_native(binary, handle.address)
        except Exception as native_err:
            print("native harness unavailable (%s); using Python harness"
                  % native_err, file=sys.stderr)
            harness = "python"
            throughput, p50_us = run_python_harness(handle)
    finally:
        handle.stop()

    print(json.dumps({
        "metric": "resnet50_tpu_shm_grpc_batch8_c4_infer_per_sec",
        "value": round(throughput, 2),
        "unit": "infer/sec",
        "vs_baseline": round(throughput / BASELINE, 4),
        "p50_latency_us": round(p50_us, 1),
        "batch": BATCH,
        "harness": harness,
    }))


if __name__ == "__main__":
    main()
